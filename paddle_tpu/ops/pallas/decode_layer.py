"""Fused "decode layer" megakernel: one Pallas launch per decode step
per transformer layer, keeping the (S, d) hidden state in VMEM across
the paged KV read, the gemms, and both RMS-norm folds.

The serving decode block (serving/engine.py) dispatches each layer's
attention, o_proj and MLP as separate XLA ops with an HBM round-trip of
the (S, 1, d) hidden state between every one. RedFuser (PAPERS.md,
arxiv 2603.10026) frames exactly this cascade as the fusion backend
compilers refuse to cross; PR 3 applied it to softmax/layer-norm
chains, this module applies it to the whole decode layer:

- **Marking** (:func:`marking`): the serving engine arms a trace-time
  context while tracing its ONE decode-block program;
  ``models/llama.py`` then wraps each decode layer's cache path (s=1,
  slot-pool positions) in a ``jax.jit``-marked region, so the layer
  appears in the traced jaxpr as ONE ``pjit`` equation named
  ``pt_decode_layer_<mode>`` with a documented positional layout
  (:data:`ARG_LAYOUT`). Marking is dormant outside the fused trace —
  the default decode path traces exactly as before.
- **Recognition + splice** live in ``passes/fusion_decode.py``: the
  pass walks the block jaxpr (recursing into the ``lax.scan`` body),
  validates the marked region really is the attention→o_proj→MLP chain
  (pattern machinery from ``passes/patterns.py``), and replaces it with
  ONE ``closed_call`` traced from :func:`build_fused_callable`.
- **The kernel** (:func:`decode_layer_paged_kernel`): grid
  ``(S, max_blocks)``; per slot the hidden-state row is DMA'd to VMEM
  once, the first grid step folds RMS-norm #1 + the q projection +
  RoPE into VMEM scratch, every step folds one arena block into the
  online softmax (int8 arenas dequantized in registers via the SAME
  ``_deq_block`` as PR 10's paged-attention kernel), and the last step
  runs o_proj, the residual, RMS-norm #2 and the SwiGLU MLP entirely
  out of VMEM — the only HBM traffic per layer is the x row in, the
  out row back, the weights and the quantized KV blocks. The k/v
  projection + arena write happen in a tiny jnp prologue (the arena
  write IS HBM traffic by definition, and k/v are (S, kvh·dh), not the
  hidden state); the kernel recomputes RMS-norm #1 for q instead of
  round-tripping it (FLOPs are free, bandwidth is not — the RedFuser
  trade).
- **Off-TPU fallback**: :func:`build_fused_callable` evaluates the
  captured original region jaxpr — the fallback IS the unfused math,
  so CPU-lane fused streams are bit-identical to unfused ones by
  construction and the quick lane can pin the whole composition
  matrix. The kernel itself is exercised on CPU via interpret mode
  (tests) and dispatched for real only on TPU.

The MLP's gate/up gemms can be chunked over the ff dim
(``ff_chunk``) — the knob the block-size autotuner
(``ops/pallas/autotune.py``) sweeps and persists per device kind.
"""
from __future__ import annotations

import contextlib
import functools
import math

import jax
import jax.numpy as jnp

from . import fused as _fused
from .paged_attention import (_deq_block, _online_update, quantize_kv,
                              _NEG)

__all__ = ["marking", "marking_active", "ARG_LAYOUT", "N_CACHE",
           "N_WEIGHTS", "MODES", "build_fused_callable",
           "decode_layer_reference", "kernel_viable"]

# ---------------------------------------------------------------------------
# marking: the trace-time handshake between the serving engine and llama
# ---------------------------------------------------------------------------

_MARKING = [0]


def marking_active() -> bool:
    """True while the serving engine is tracing its decode block for
    megakernel fusion (models mark their decode layers only then)."""
    return bool(_MARKING[0])


@contextlib.contextmanager
def marking():
    """Arm decode-layer marking for the duration of one trace."""
    _MARKING[0] += 1
    try:
        yield
    finally:
        _MARKING[0] -= 1


# the marked pjit's positional contract — the fusion pass and the model
# agree on THIS, not on matching 200 primitives through the rope chain.
# aux is the dense per-row pad vector or the paged block table; eps are
# Literal scalars (concrete at trace time, validated by the pass).
ARG_LAYOUT = ("x", "cos", "sin", "eps1", "eps2", "pos", "aux",
              "*cache", "*weights")
WEIGHT_NAMES = ("ln1", "wq", "wk", "wv", "wo", "ln2", "wg", "wu", "wd")
N_WEIGHTS = len(WEIGHT_NAMES)
N_CACHE = {"dense": 2, "paged": 2, "paged_int8": 4}
MODES = tuple(N_CACHE)
N_FIXED = 7          # x, cos, sin, eps1, eps2, pos, aux


def split_args(mode: str, args):
    """(fixed, cache, weights) views over the flat marked-call args."""
    nc = N_CACHE[mode]
    fixed = args[:N_FIXED]
    cache = args[N_FIXED:N_FIXED + nc]
    wts = args[N_FIXED + nc:]
    return fixed, cache, wts


def _rot_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


# ---------------------------------------------------------------------------
# reference: the unfused math, restated — the kernel-parity oracle
# ---------------------------------------------------------------------------

def decode_layer_reference(mode, x, cos, sin, eps1, eps2, pos, aux,
                           *rest):
    """One decode layer in plain jnp, mirroring the exact math of the
    unfused llama cache path at s=1 (RMSNorm as ``_rms_ref``, per-row
    RoPE, the ``cached_attention`` write/read discipline, SwiGLU MLP).
    THE parity oracle for the Pallas megakernel — production fallback
    instead evaluates the captured original jaxpr (bit-exact by
    construction); tests pin this restatement against that jaxpr too,
    so the oracle can never drift from the model."""
    from . import paged_attention as _pa
    (cache, wts) = split_args(mode, (None,) * N_FIXED + tuple(rest))[1:]
    ln1, wq, wk, wv, wo, ln2, wg, wu, wd = wts
    S, s, d = x.shape
    dh = cos.shape[1]
    h = wq.shape[1] // dh
    kvh = wk.shape[1] // dh
    scale = 1.0 / math.sqrt(dh)
    pos = jnp.asarray(pos, jnp.int32)

    def rms(v, w, eps):
        vf = v.astype(jnp.float32)
        var = jnp.mean(vf * vf, axis=-1, keepdims=True)
        return (vf * jax.lax.rsqrt(var + eps)).astype(v.dtype) * w

    r1 = rms(x, ln1, eps1)
    q = (r1 @ wq).reshape(S, s, h, dh)
    k = (r1 @ wk).reshape(S, s, kvh, dh)
    v = (r1 @ wv).reshape(S, s, kvh, dh)
    pad = aux if mode == "dense" else jnp.zeros((S,), jnp.int32)
    positions = jnp.clip(pos[:, None] + jnp.arange(s)[None, :]
                         - pad[:, None], 0, None)
    c = cos[positions].astype(x.dtype)          # (S, 1, dh)
    sn = sin[positions].astype(x.dtype)

    def rope(t):
        return t * c[:, :, None, :] + _rot_half(t) * sn[:, :, None, :]

    q, k = rope(q), rope(k)
    if mode == "dense":
        ckv, cvv = cache

        def upd(cachev, blockv):
            return jax.vmap(
                lambda cr, xr, p: jax.lax.dynamic_update_slice(
                    cr, xr, (p, 0, 0)))(cachev,
                                        blockv.astype(cachev.dtype), pos)

        ck, cv = upd(ckv, k), upd(cvv, v)
        t_idx = jnp.arange(ck.shape[1])
        qg = q.reshape(S, s, kvh, h // kvh, dh).astype(jnp.float32)
        scores = jnp.einsum("bskgd,btkd->bkgst", qg,
                            ck.astype(jnp.float32)) * scale
        mask = t_idx[None, None, :] <= pos[:, None, None]
        mask = mask & (t_idx[None, None, :] >= pad[:, None, None])
        scores = jnp.where(mask[:, None, None], scores,
                           jnp.float32(-1e30))
        probs = jax.nn.softmax(scores, axis=-1).astype(cv.dtype)
        out = jnp.einsum("bkgst,btkd->bskgd", probs, cv)
        out = out.reshape(S, s, h, dh).astype(q.dtype)
        new_cache = (ck, cv)
    else:
        tbl = aux
        bs = cache[0].shape[1]
        mb = tbl.shape[1]
        tpos = pos[:, None]                       # (S, 1), s == 1
        blk_idx = tpos // bs
        oob = blk_idx >= mb
        blk = jnp.where(oob, 0, jnp.take_along_axis(
            tbl, jnp.clip(blk_idx, 0, mb - 1), axis=1))
        off = jnp.where(oob, 0, tpos % bs)
        if mode == "paged_int8":
            ckv, cvv, skv, svv = cache
            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            ck = ckv.at[blk, off].set(kq.astype(ckv.dtype))
            cv = cvv.at[blk, off].set(vq.astype(cvv.dtype))
            sk = skv.at[blk, off].set(ks)
            sv = svv.at[blk, off].set(vs)
            out = _pa.paged_attention_decode_int8(
                q[:, 0], ck, cv, sk, sv, tbl, pos + 1,
                scale=scale)[:, None].astype(q.dtype)
            new_cache = (ck, cv, sk, sv)
        else:
            ckv, cvv = cache
            ck = ckv.at[blk, off].set(k.astype(ckv.dtype))
            cv = cvv.at[blk, off].set(v.astype(cvv.dtype))
            out = _pa.paged_attention_reference(
                q, ck, cv, tbl, pos + 1, scale=scale)
            new_cache = (ck, cv)
    o = out.reshape(S, s, h * dh) @ wo
    h1 = x + o
    r2 = rms(h1, ln2, eps2)
    g1 = r2 @ wg
    act = jax.nn.silu(g1) * (r2 @ wu)
    return (h1 + act @ wd,) + new_cache


# ---------------------------------------------------------------------------
# the Pallas megakernel (paged modes, s == 1)
# ---------------------------------------------------------------------------

# VMEM the resident set may claim (weights + arena block + scratch);
# configs past this fall back to the unfused-math path, loudly visible
# via engine.megakernel_kernel_eligible()
_VMEM_BUDGET = 10 << 20


def _weight_bytes(d, h, kvh, dh, ff):
    return 4 * (d * h * dh          # wq (reshaped (d, h, dh))
                + h * dh * d        # wo
                + 2 * d * ff        # wg, wu
                + ff * d            # wd
                + 2 * d)            # both norm weights


def kernel_viable(mode, x_aval, cache_avals, wt_avals, window=None
                  ) -> bool:
    """Static routing gate for the megakernel: paged modes only, fp32
    hidden state/weights, no sliding window, and the resident set
    (weights + one arena block + scratch) within the VMEM budget.
    Everything else takes the bit-exact fallback."""
    if mode not in ("paged", "paged_int8") or window is not None:
        return False
    if not _fused._pallas_ok():
        return False
    if x_aval.dtype != jnp.float32:
        return False
    if any(w.dtype != jnp.float32 for w in wt_avals):
        return False
    d = x_aval.shape[-1]
    wq, wk = wt_avals[1], wt_avals[2]
    ff = wt_avals[6].shape[1]
    bs, kvh = cache_avals[0].shape[1], cache_avals[0].shape[2]
    dh = cache_avals[0].shape[3]
    if dh % 2 != 0:
        return False                 # rotate-half needs an even head dim
    h = wq.shape[1] // dh
    kv_blk = bs * kvh * dh * (1 if mode == "paged_int8" else 4) * 2
    scratch = 4 * (3 * kvh * (h // kvh) * dh + 2 * d + ff)
    return (_weight_bytes(d, h, kvh, dh, ff) + kv_blk + scratch
            <= _VMEM_BUDGET)


def _tuned_ff_chunk(d: int, ff: int) -> int:
    """MLP ff-dim compute-chunk: the autotuner's knob for this kernel
    (one entry per (d, ff) per device kind). Falls back to the whole ff
    (no chunking) — a tuned chunk must divide ff and stay 128-aligned
    or it is ignored."""
    from .autotune import lookup
    cfg = lookup("decode_layer", {"d": d, "ff": ff})
    if cfg:
        fc = int(cfg.get("ff_chunk", 0))
        if fc > 0 and ff % fc == 0 and fc % 128 == 0:
            return fc
    return ff


def _mega_kernel(tbl_ref, len_ref, x_ref, cos_ref, sin_ref, ln1_ref,
                 wq_ref, wo_ref, ln2_ref, wg_ref, wu_ref, wd_ref,
                 *kv_refs_and_out, bs, scale, nblocks, eps1, eps2,
                 int8, ff_chunk):
    """One grid step = (slot i, table entry j). Scratch (per slot):
    the RoPE'd q and the online-softmax (m, l, acc) — the hidden state
    never leaves VMEM between the attention read, o_proj, the residual
    folds and the MLP."""
    from jax.experimental import pallas as pl

    if int8:
        k_ref, v_ref, sk_ref, sv_ref = kv_refs_and_out[:4]
        o_ref, q_s, m_ref, l_ref, acc_ref = kv_refs_and_out[4:]
    else:
        k_ref, v_ref = kv_refs_and_out[:2]
        o_ref, q_s, m_ref, l_ref, acc_ref = kv_refs_and_out[2:]
    i = pl.program_id(0)
    j = pl.program_id(1)
    kvh, g, dh = acc_ref.shape
    h = kvh * g

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)
        # RMS-norm #1 + q projection + RoPE, straight into VMEM scratch
        xr = x_ref[...].astype(jnp.float32)            # (1, d)
        var = jnp.mean(xr * xr, axis=-1, keepdims=True)
        r1 = xr * jax.lax.rsqrt(var + eps1) * ln1_ref[...]
        q = jnp.einsum("od,dhk->ohk", r1, wq_ref[...])[0]   # (h, dh)
        c = cos_ref[...]                               # (1, dh)
        sn = sin_ref[...]
        q = q * c + _rot_half(q) * sn
        q_s[...] = q.reshape(kvh, g, dh)

    length = len_ref[i]

    @pl.when(j * bs < length)
    def _block():
        if int8:
            k = _deq_block(k_ref[0], sk_ref[0])
            v = _deq_block(v_ref[0], sv_ref[0])
        else:
            k = k_ref[0].astype(jnp.float32)
            v = v_ref[0].astype(jnp.float32)
        _online_update(q_s[...].reshape(h, dh), k, v, j, bs, length,
                       scale, m_ref, l_ref, acc_ref)

    @pl.when(j == nblocks - 1)
    def _finalize():
        attn = (acc_ref[...] / l_ref[...]).reshape(1, h * dh)
        o = jnp.dot(attn, wo_ref[...],
                    preferred_element_type=jnp.float32)
        h1 = x_ref[...].astype(jnp.float32) + o        # residual #1
        var = jnp.mean(h1 * h1, axis=-1, keepdims=True)
        r2 = h1 * jax.lax.rsqrt(var + eps2) * ln2_ref[...]
        ff = wg_ref.shape[1]
        if ff_chunk >= ff:
            g1 = jnp.dot(r2, wg_ref[...],
                         preferred_element_type=jnp.float32)
            u = jnp.dot(r2, wu_ref[...],
                        preferred_element_type=jnp.float32)
            act = g1 * jax.nn.sigmoid(g1) * u          # silu(g) * u
            mlp = jnp.dot(act, wd_ref[...],
                          preferred_element_type=jnp.float32)
        else:
            def body(ci, acc):
                sl = pl.ds(ci * ff_chunk, ff_chunk)
                gc = jnp.dot(r2, wg_ref[:, sl],
                             preferred_element_type=jnp.float32)
                uc = jnp.dot(r2, wu_ref[:, sl],
                             preferred_element_type=jnp.float32)
                ac = gc * jax.nn.sigmoid(gc) * uc
                return acc + jnp.dot(ac, wd_ref[sl, :],
                                     preferred_element_type=jnp.float32)
            mlp = jax.lax.fori_loop(0, ff // ff_chunk, body,
                                    jnp.zeros((1, h1.shape[-1]),
                                              jnp.float32))
        o_ref[...] = (h1 + mlp).astype(o_ref.dtype)


def decode_layer_paged_kernel(mode, x, cos, sin, eps1, eps2, pos, tbl,
                              *rest):
    """The megakernel path: jnp prologue (k/v projection + RoPE + arena
    write — mirrors ``cached_attention``'s s=1 discipline, trash-block
    OOB routing included) followed by ONE ``pallas_call`` for
    everything from RMS-norm #1/q through the MLP residual."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    (cache, wts) = split_args(mode, (None,) * N_FIXED + tuple(rest))[1:]
    ln1, wq, wk, wv, wo, ln2, wg, wu, wd = wts
    S, s, d = x.shape
    dh = cos.shape[1]
    h = wq.shape[1] // dh
    kvh = wk.shape[1] // dh
    ff = wg.shape[1]
    scale = 1.0 / math.sqrt(dh)
    pos = jnp.asarray(pos, jnp.int32)
    bs = cache[0].shape[1]
    mb = tbl.shape[1]
    int8 = mode == "paged_int8"

    # ---- prologue: k/v projection + RoPE + arena write (jnp) ----------
    xf = x[:, 0].astype(jnp.float32)                   # (S, d)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    r1 = xf * jax.lax.rsqrt(var + eps1) * ln1
    k = (r1 @ wk).reshape(S, kvh, dh)
    v = (r1 @ wv).reshape(S, kvh, dh)
    c = cos[pos].astype(jnp.float32)                   # (S, dh)
    sn = sin[pos].astype(jnp.float32)
    k = k * c[:, None, :] + _rot_half(k) * sn[:, None, :]
    blk_idx = pos // bs
    oob = blk_idx >= mb
    blk = jnp.where(oob, 0, jnp.take_along_axis(
        tbl, jnp.clip(blk_idx, 0, mb - 1)[:, None], axis=1)[:, 0])
    off = jnp.where(oob, 0, pos % bs)
    if int8:
        ckv, cvv, skv, svv = cache
        kq, ks = quantize_kv(k)
        vq, vs = quantize_kv(v)
        ck = ckv.at[blk, off].set(kq.astype(ckv.dtype))
        cv = cvv.at[blk, off].set(vq.astype(cvv.dtype))
        sk = skv.at[blk, off].set(ks)
        sv = svv.at[blk, off].set(vs)
        new_cache = (ck, cv, sk, sv)
    else:
        ckv, cvv = cache
        ck = ckv.at[blk, off].set(k.astype(ckv.dtype))
        cv = cvv.at[blk, off].set(v.astype(cvv.dtype))
        new_cache = (ck, cv)

    # ---- the megakernel ----------------------------------------------
    def kv_spec():
        return pl.BlockSpec((1, bs, kvh, dh),
                            lambda i, j, tbl, lens: (tbl[i, j], 0, 0, 0))

    def sc_spec():
        return pl.BlockSpec((1, bs, kvh),
                            lambda i, j, tbl, lens: (tbl[i, j], 0, 0))

    def row(shape):
        return pl.BlockSpec(shape, lambda i, j, tbl, lens: (i,)
                            + (0,) * (len(shape) - 1))

    def whole(arr):
        nd = arr.ndim
        return pl.BlockSpec(arr.shape,
                            lambda i, j, tbl, lens: (0,) * nd)

    wq3 = wq.reshape(d, h, dh)        # weight relayout, not a per-token
    ln1_2 = ln1.reshape(1, d)         # hidden-state round trip
    ln2_2 = ln2.reshape(1, d)
    in_specs = [row((1, d)), row((1, dh)), row((1, dh)),
                whole(ln1_2), whole(wq3), whole(wo), whole(ln2_2),
                whole(wg), whole(wu), whole(wd),
                kv_spec(), kv_spec()]
    operands = [tbl, pos + 1, x[:, 0], c, sn, ln1_2, wq3, wo, ln2_2,
                wg, wu, wd, new_cache[0], new_cache[1]]
    if int8:
        in_specs += [sc_spec(), sc_spec()]
        operands += [new_cache[2], new_cache[3]]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, d),
                               lambda i, j, tbl, lens: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((kvh, h // kvh, dh), jnp.float32),   # RoPE'd q
            pltpu.VMEM((kvh, h // kvh, 1), jnp.float32),    # m
            pltpu.VMEM((kvh, h // kvh, 1), jnp.float32),    # l
            pltpu.VMEM((kvh, h // kvh, dh), jnp.float32),   # acc
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _mega_kernel, bs=bs, scale=scale, nblocks=mb,
            eps1=float(eps1), eps2=float(eps2), int8=int8,
            ff_chunk=_tuned_ff_chunk(d, ff)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, d), x.dtype),
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_fused._FORCE_INTERPRET,
    )(*operands)
    return (out[:, None, :],) + new_cache


# ---------------------------------------------------------------------------
# the fused callable the pass splices (kernel on TPU, captured-jaxpr
# fallback everywhere else)
# ---------------------------------------------------------------------------

def build_fused_callable(mode, inner_closed, eps1, eps2, *,
                         allow_kernel=True):
    """Build the replacement for one marked decode layer. The returned
    function's __name__ is ``pt_fused_decode_layer`` — the handle the
    no-transient jaxpr walks key on (``call_jaxpr.jaxpr.debug_info``).

    Kernel routing is decided ONCE at trace time from the avals;
    ineligible shapes/modes (and ``allow_kernel=False`` — the
    weight-quant engines, where the in-graph dequant must stay fused
    into the XLA gemm prologue) evaluate the captured original jaxpr,
    which is the unfused math bit-for-bit."""
    import jax.core as jcore

    invars = inner_closed.jaxpr.invars

    def _use_kernel():
        if not allow_kernel:
            return False
        fixed, cache, wts = split_args(
            mode, tuple(v.aval for v in invars))
        return kernel_viable(mode, fixed[0], cache, wts)

    use_kernel = _use_kernel()

    def pt_fused_decode_layer(*args):
        if use_kernel:
            fixed, cache, wts = split_args(mode, args)
            return decode_layer_paged_kernel(
                mode, fixed[0], fixed[1], fixed[2], eps1, eps2,
                fixed[5], fixed[6], *cache, *wts)
        return tuple(jcore.jaxpr_as_fun(inner_closed)(*args))

    pt_fused_decode_layer.uses_kernel = use_kernel
    return pt_fused_decode_layer
