"""Fused softmax-cross-entropy: one-pass online logsumexp + label
gather, no ``(N, vocab)`` probability / one-hot intermediates.

Reference parity: the PHI fused softmax_with_cross_entropy CUDA kernel
(paddle/phi/kernels/gpu/cross_entropy_kernel.cu — verify) computes the
per-row loss with a warp-level online softmax; the unfused jaxpr
(one_hot -> mul -> reduce) materializes TWO (N, V) temporaries on top
of the logits. RedFuser (PAPERS.md, arxiv 2603.10026) shows exactly
this cascaded-reduction shape (max -> exp-sum -> gather) is what
accelerator compilers fail to fuse on their own.

TPU-native design: a single Pallas launch per row-block reads the
logits tile once from HBM and produces the per-row ``lse`` and target
log-prob; the backward is a second one-pass kernel writing
``p*ga - onehot*gb`` straight to the cotangent (the only full-width
array it touches IS the returned gradient). Off-TPU the same math runs
as a ``lax.scan`` over vocab chunks — transients stay (N, V/chunks),
so even the fallback jaxpr contains no vocab-sized intermediate, which
tests assert by walking the traced program (see tests/test_passes.py).

Everything is wired behind ``custom_vjp``: fusion passes can splice the
forward into a traced program and gradients still route through the
hand-written backward.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import fused as _fused

__all__ = ["softmax_xent_rows", "softmax_xent_rows_reference"]

# finite stand-in for -inf inside kernels: keeps padded/garbage rows
# from producing inf-inf=nan while being far below any real logit
_NEG = -1e30


def _tuned_chunk_cap(v: int, default: int = 4096) -> int:
    """The fallback's chunk cap: the autotune table's winner for this
    vocab on this device kind when a valid (stamp-matching) entry
    exists, the documented 4096 otherwise — regression-pinned in
    tests/test_autotune.py."""
    from .autotune import lookup
    cfg = lookup("xent", {"vocab": v})
    if cfg:
        cap = int(cfg.get("chunk_cap", 0))
        if cap > 0:
            return cap
    return default


def _best_chunk(v: int, cap: int = None) -> int:
    """Largest divisor of ``v`` that is <= cap (prefers >= 128).
    ``cap=None`` consults the autotune table (fallback 4096)."""
    if cap is None:
        cap = _tuned_chunk_cap(v)
    for c in range(min(v, cap), 127, -1):
        if v % c == 0:
            return c
    return v


# ---------------------------------------------------------------------------
# chunked-scan fallback (CPU / non-aligned shapes): (N, chunk) transients
# ---------------------------------------------------------------------------

def _rows_scan_fwd(x, labels, chunk_cap=None):
    n, v = x.shape
    c = _best_chunk(v, chunk_cap)
    if c == v:
        xf = x.astype(jnp.float32)
        m = jnp.max(xf, axis=-1)
        s = jnp.sum(jnp.exp(xf - m[:, None]), axis=-1)
        lse = m + jnp.log(s)
        tgt = jnp.take_along_axis(xf, labels[:, None], axis=1)[:, 0]
        return lse - tgt, lse
    nchunks = v // c

    def body(carry, i):
        m, s, tgt = carry
        xc = jax.lax.dynamic_slice_in_dim(x, i * c, c, 1).astype(jnp.float32)
        m_new = jnp.maximum(m, jnp.max(xc, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(xc - m_new[:, None]), axis=-1)
        in_chunk = (labels >= i * c) & (labels < (i + 1) * c)
        idx = jnp.clip(labels - i * c, 0, c - 1)
        lt = jnp.take_along_axis(xc, idx[:, None], axis=1)[:, 0]
        tgt = jnp.where(in_chunk, lt, tgt)
        return (m_new, s, tgt), None

    init = (jnp.full((n,), _NEG, jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32))
    (m, s, tgt), _ = jax.lax.scan(body, init, jnp.arange(nchunks))
    lse = m + jnp.log(s)
    return lse - tgt, lse


def _rows_scan_bwd(x, labels, lse, ga, gb):
    """dx = softmax * ga[:,None] - onehot * gb[:,None], chunk-wise."""
    n, v = x.shape
    c = _best_chunk(v)
    nchunks = v // c

    def chunk_grad(i):
        xc = jax.lax.dynamic_slice_in_dim(x, i * c, c, 1).astype(jnp.float32)
        p = jnp.exp(xc - lse[:, None])
        idx = jnp.clip(labels - i * c, 0, c - 1)
        in_chunk = (labels >= i * c) & (labels < (i + 1) * c)
        onehot = (jnp.arange(c)[None, :] == idx[:, None]) & in_chunk[:, None]
        return p * ga[:, None] - onehot.astype(jnp.float32) * gb[:, None]

    if nchunks == 1:
        return chunk_grad(0).astype(x.dtype)
    _, dxs = jax.lax.scan(lambda _, i: (None, chunk_grad(i)), None,
                          jnp.arange(nchunks))
    return dxs.transpose(1, 0, 2).reshape(n, v).astype(x.dtype)


# ---------------------------------------------------------------------------
# Pallas kernels: one pass over the logits tile per direction
# ---------------------------------------------------------------------------

def _xent_fwd_kernel(x_ref, lab_ref, nll_ref, lse_ref):
    x = x_ref[...].astype(jnp.float32)                 # (R, V)
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    m = jnp.max(x, axis=-1, keepdims=True)
    s = jnp.sum(jnp.exp(x - m), axis=-1, keepdims=True)
    lse = m + jnp.log(s)
    hit = cols == lab_ref[...]                         # (R, V) vs (R, 1)
    tgt = jnp.max(jnp.where(hit, x, _NEG), axis=-1, keepdims=True)
    nll_ref[...] = lse - tgt
    lse_ref[...] = lse


def _xent_bwd_kernel(x_ref, lab_ref, lse_ref, ga_ref, gb_ref, dx_ref):
    x = x_ref[...].astype(jnp.float32)
    p = jnp.exp(x - lse_ref[...])
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    onehot = (cols == lab_ref[...]).astype(jnp.float32)
    dx_ref[...] = (p * ga_ref[...]
                   - onehot * gb_ref[...]).astype(dx_ref.dtype)


def _block_rows(v: int) -> int:
    # ~2 MB fp32 tile budget; rows in multiples of the 8-sublane VPU
    budget = (2 << 20) // max(v * 4, 1)
    return max(8, min(256, budget // 8 * 8))


def _pallas_viable(x) -> bool:
    n, v = x.shape
    return _fused._pallas_ok() and v % 128 == 0 and v * 4 * 8 <= (4 << 20)


def _rows_pallas_fwd(x, labels):
    from jax.experimental import pallas as pl

    n, v = x.shape
    br = _block_rows(v)
    grid = (pl.cdiv(n, br),)
    xspec = pl.BlockSpec((br, v), lambda i: (i, 0))
    cspec = pl.BlockSpec((br, 1), lambda i: (i, 0))
    nll, lse = pl.pallas_call(
        _xent_fwd_kernel,
        grid=grid,
        in_specs=[xspec, cspec],
        out_specs=[cspec, cspec],
        out_shape=[jax.ShapeDtypeStruct((n, 1), jnp.float32),
                   jax.ShapeDtypeStruct((n, 1), jnp.float32)],
        interpret=_fused._FORCE_INTERPRET,
    )(x, labels[:, None])
    return nll[:, 0], lse[:, 0]


def _rows_pallas_bwd(x, labels, lse, ga, gb):
    from jax.experimental import pallas as pl

    n, v = x.shape
    br = _block_rows(v)
    grid = (pl.cdiv(n, br),)
    xspec = pl.BlockSpec((br, v), lambda i: (i, 0))
    cspec = pl.BlockSpec((br, 1), lambda i: (i, 0))
    dx = pl.pallas_call(
        _xent_bwd_kernel,
        grid=grid,
        in_specs=[xspec, cspec, cspec, cspec, cspec],
        out_specs=xspec,
        out_shape=jax.ShapeDtypeStruct((n, v), x.dtype),
        interpret=_fused._FORCE_INTERPRET,
    )(x, labels[:, None], lse[:, None], ga[:, None], gb[:, None])
    return dx


# ---------------------------------------------------------------------------
# custom_vjp entry point
# ---------------------------------------------------------------------------

@jax.custom_vjp
def softmax_xent_rows(x, labels):
    """Per-row softmax cross-entropy core.

    x: (N, V) logits (any float dtype; accumulation is fp32);
    labels: (N,) int32/int64, REQUIRED in-range [0, V) (callers clip —
    ignore_index masking composes outside on the returned rows).
    Returns ``(nll, lse)``: nll[i] = lse[i] - x[i, labels[i]] and the
    per-row logsumexp, both (N,) fp32. Differentiable wrt ``x`` through
    BOTH outputs (d lse/dx = softmax), so label-smoothing algebra on top
    of (nll, lse) has exact gradients.
    """
    out, _ = _rows_fwd(x, labels)
    return out


def _rows_fwd(x, labels):
    labels = labels.astype(jnp.int32)
    if _pallas_viable(x):
        nll, lse = _rows_pallas_fwd(x, labels)
    else:
        nll, lse = _rows_scan_fwd(x, labels)
    return (nll, lse), (x, labels, lse)


def _rows_bwd(res, cts):
    x, labels, lse = res
    g_nll, g_lse = cts
    ga = (g_nll + g_lse).astype(jnp.float32)   # softmax term scale
    gb = g_nll.astype(jnp.float32)             # one-hot term scale
    if _pallas_viable(x):
        dx = _rows_pallas_bwd(x, labels, lse, ga, gb)
    else:
        dx = _rows_scan_bwd(x, labels, lse, ga, gb)
    return dx, None


softmax_xent_rows.defvjp(_rows_fwd, _rows_bwd)


def softmax_xent_rows_reference(x, labels):
    """Unfused parity oracle: full log_softmax + gather (materializes
    (N, V) — tests pin the fused path against this)."""
    logp = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
    tgt = jnp.take_along_axis(logp, labels.astype(jnp.int32)[:, None],
                              axis=1)[:, 0]
    lse = jnp.max(x.astype(jnp.float32), axis=-1) + jnp.log(
        jnp.sum(jnp.exp(x.astype(jnp.float32)
                        - jnp.max(x.astype(jnp.float32), axis=-1,
                                  keepdims=True)), axis=-1))
    return -tgt, lse
