"""Pallas/Mosaic TPU kernels for the fused hot set (reference's CUDA fused
kernels: paddle/phi/kernels/fusion/, flash_attn — verify). Each kernel has an
XLA fallback used on CPU / when shapes don't fit the kernel grid."""
from . import flash_attention   # noqa: F401
from . import paged_attention   # noqa: F401
from . import xent              # noqa: F401
