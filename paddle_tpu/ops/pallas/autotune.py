"""Pallas block-size autotuner: sweep → provenance-stamped table →
trace-time lookup.

Every Pallas kernel in the repo used to hard-code its block shapes
(flash 512-class tiles from one v5e profile, xent's 4096 chunk cap,
the paged arena's block_size=16). Those constants are device-kind
facts, not code facts — this module gives them a measured home:

- **Table**: one JSON file (``PT_TUNE_TABLE`` or
  ``~/.cache/paddle_tpu/tune_table.json``) holding per-kernel winning
  configs keyed by ``kernel | device_kind | sorted(key=value,...)``,
  stamped with the SAME provenance fields as every bench artifact
  (PR 7): jax/jaxlib versions, device kind, git rev, UTC.
- **Staleness**: a table whose stamp disagrees with the RUNNING
  environment (different jaxlib or device kind) is never consulted
  silently — :func:`lookup` warns once and reports misses, and
  ``tools/tier1.sh`` prints the same verdict up front. Re-sweep to
  refresh; :func:`record` starts a fresh table rather than mixing
  provenances.
- **Consumers** (all at trace time, fallback defaults documented in
  each): ``xent._best_chunk`` (chunk cap), ``flash_attention``
  (splash/flash block preferences, with the effective choice
  attributable via :func:`last_block_choice`), the paged engine's
  default ``block_size``, and the decode megakernel's MLP
  ``ff_chunk``.
- **Sweeps** (:func:`run_autotune`): xent vocab-chunk and the paged
  arena block size measure real work on ANY backend (the CPU lane's
  numbers tune the CPU lane); the flash/splash block and megakernel
  ff-chunk sweeps only run where the kernels do (TPU) and are recorded
  as skipped elsewhere — a CPU-stamped table never smuggles CPU
  timings into TPU kernels because the device-kind key and stamp both
  change.

Lookups are counted (``pt_autotune_lookups_total{kernel,result}``) so
a serving fleet can see tuner hit/miss/stale rates next to the pass
rewrite counters.
"""
from __future__ import annotations

import json
import os
import time
import warnings
from typing import Dict, Optional

from ...observability import metrics as _om
from ...utils.flags import env_str

__all__ = ["table_path", "current_stamp", "stamp_matches", "load_table",
           "lookup", "record", "tuned_paged_block_size", "run_autotune"]

_M_LOOKUPS = _om.counter(
    "pt_autotune_lookups_total",
    "autotune-table lookups by kernel and result (hit/miss/stale)",
    labels=("kernel", "result"))

_DEFAULT_PATH = os.path.join(os.path.expanduser("~"), ".cache",
                             "paddle_tpu", "tune_table.json")


def table_path() -> str:
    """Resolved tuning-table location (``PT_TUNE_TABLE`` overrides the
    per-user cache default)."""
    return env_str("PT_TUNE_TABLE") or _DEFAULT_PATH


def _device_kind() -> str:
    import jax
    try:
        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


# process-constant stamp fields, resolved once: stamp_matches() runs on
# EVERY trace-time lookup against a present table, and forking
# `git rev-parse` / scanning package metadata per kernel trace would be
# pure waste (jaxlib version and device kind cannot change in-process)
_ENV_STAMP: dict = {}


def _env_stamp() -> dict:
    if not _ENV_STAMP:
        import importlib.metadata as md

        def _v(pkg):
            try:
                return md.version(pkg)
            except md.PackageNotFoundError:
                return None

        try:
            import subprocess
            rev = subprocess.run(
                ["git", "-C", os.path.dirname(os.path.abspath(__file__)),
                 "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True,
                timeout=10).stdout.strip() or None
        except Exception:
            rev = None
        _ENV_STAMP.update(
            jax_version=_v("jax"), jaxlib_version=_v("jaxlib"),
            device_kind=_device_kind(), git_rev=rev)
    return _ENV_STAMP


def current_stamp() -> dict:
    """The provenance stamp (PR 7 bench format: software stack + source
    rev + device kind) a table written NOW would carry."""
    return dict(_env_stamp(),
                tuned_utc=time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime()))


def stamp_matches(stamp: dict) -> tuple:
    """(ok, reason): whether a table stamp is valid for the RUNNING
    environment. jaxlib version and device kind are the block-shape-
    bearing facts; jax version and git rev are recorded for the paper
    trail but do not invalidate (block shapes survive frontend
    changes)."""
    cur = _env_stamp()
    for field in ("jaxlib_version", "device_kind"):
        if stamp.get(field) != cur[field]:
            return False, (f"{field} mismatch: table has "
                           f"{stamp.get(field)!r}, running "
                           f"{cur[field]!r}")
    return True, "ok"


def _entry_key(kernel: str, key: Dict) -> str:
    parts = ",".join(f"{k}={key[k]}" for k in sorted(key))
    return f"{kernel}|{_device_kind()}|{parts}"


# per-path cache: (mtime, parsed-table-or-None, stale_reason)
_CACHE: Dict[str, tuple] = {}
_WARNED: set = set()


def load_table(path: Optional[str] = None) -> Optional[dict]:
    """Parse the table at ``path`` (cached by mtime); None when absent
    or unreadable. Staleness is judged at lookup, not load."""
    path = path or table_path()
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        _CACHE.pop(path, None)
        return None
    hit = _CACHE.get(path)
    if hit is not None and hit[0] == mtime:
        return hit[1]
    try:
        with open(path) as f:
            table = json.load(f)
        if not isinstance(table.get("entries"), dict):
            raise ValueError("no entries dict")
    except (OSError, ValueError, json.JSONDecodeError):
        table = None
    _CACHE[path] = (mtime, table, None)
    return table


def lookup(kernel: str, key: Dict, path: Optional[str] = None
           ) -> Optional[dict]:
    """Trace-time consult: the winning config dict for ``kernel`` under
    ``key`` on THIS device kind, or None (missing table/entry, or a
    stale stamp — never silently served). Counted per result."""
    path = path or table_path()
    table = load_table(path)
    if table is None:
        _M_LOOKUPS.inc(kernel=kernel, result="miss")
        return None
    ok, reason = stamp_matches(table.get("stamp", {}))
    if not ok:
        if path not in _WARNED:
            _WARNED.add(path)
            warnings.warn(
                f"autotune table {path} is STALE ({reason}) — kernels "
                "fall back to their documented defaults; re-run the "
                "autotune sweep (bench.py autotune stage) to refresh",
                RuntimeWarning)
        _M_LOOKUPS.inc(kernel=kernel, result="stale")
        return None
    entry = table["entries"].get(_entry_key(kernel, key))
    if entry is None:
        _M_LOOKUPS.inc(kernel=kernel, result="miss")
        return None
    _M_LOOKUPS.inc(kernel=kernel, result="hit")
    return dict(entry.get("config", {}))


def record(kernel: str, key: Dict, config: Dict, measured_ms: float,
           path: Optional[str] = None, candidates: int = 0) -> str:
    """Persist one sweep winner (atomic tmp+rename). A pre-existing
    table with a MISMATCHED stamp is replaced wholesale — mixing
    provenances inside one file would defeat the staleness contract."""
    from ...distributed.checkpoint import atomic_json_dump
    path = path or table_path()
    table = load_table(path)
    if table is not None and not stamp_matches(
            table.get("stamp", {}))[0]:
        table = None            # stale: start fresh, never mix stamps
    if table is None:
        table = {"entries": {}}
    table["stamp"] = current_stamp()
    table["entries"][_entry_key(kernel, key)] = {
        "kernel": kernel, "key": dict(key), "config": dict(config),
        "measured_ms": round(float(measured_ms), 4),
        "candidates": int(candidates)}
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    atomic_json_dump(path, table)
    _CACHE.pop(path, None)
    return path


def tuned_paged_block_size(default: int = 16) -> int:
    """The paged engine's default arena block size: tuned entry when a
    valid table has one, the documented default (16) otherwise. The
    explicit ``block_size=`` / ``PT_SERVING_BLOCK_SIZE`` knobs always
    win (resolution lives in serving/paging.py)."""
    cfg = lookup("paged_attention", {"knob": "block_size"})
    if cfg:
        bs = int(cfg.get("block_size", 0))
        if bs > 0:
            return bs
    return default


# ---------------------------------------------------------------------------
# sweeps
# ---------------------------------------------------------------------------

def _time_best(candidates, fn, reps: int = 3):
    """(best_candidate, best_ms, {candidate: ms}): median-free min-of-
    reps timing — the sweep wants the fastest config, and min is the
    noise-robust estimator for 'how fast can this go'."""
    results = {}
    for cand in candidates:
        fn(cand)                            # compile/warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn(cand)
            best = min(best, time.perf_counter() - t0)
        results[cand] = best * 1000.0
    winner = min(results, key=results.get)
    return winner, results[winner], results


def autotune_xent(rows: int = 256, vocab: int = 8192,
                  path: Optional[str] = None) -> dict:
    """Sweep the xent fallback's vocab-chunk cap (the (N, chunk)
    transient size vs scan-step count trade — real work on every
    backend) and persist the winner for THIS (rows-class, vocab)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from .xent import _rows_scan_fwd

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(rows, vocab).astype(np.float32))
    labels = jnp.asarray(rs.randint(0, vocab, (rows,)).astype(np.int32))
    # candidates are CAPS; _best_chunk resolves each to the largest
    # divisor of vocab it allows — dedupe on the EFFECTIVE chunk so a
    # non-power-of-two vocab (e.g. 32000) still sweeps distinct real
    # schedules instead of crashing on an empty list
    from .xent import _best_chunk
    cands = sorted({_best_chunk(vocab, c)
                    for c in (512, 1024, 2048, 4096, 8192)})

    jitted = {c: jax.jit(lambda xv, lv, _c=c: _rows_scan_fwd(
        xv, lv, chunk_cap=_c)) for c in cands}

    def run(c):
        nll, lse = jitted[c](x, labels)
        jax.block_until_ready((nll, lse))

    winner, ms, results = _time_best(cands, run)
    key = {"vocab": vocab}
    record("xent", key, {"chunk_cap": winner}, ms, path=path,
           candidates=len(cands))
    return {"kernel": "xent", "key": key, "winner": {"chunk_cap": winner},
            "ms": {str(k): round(v, 3) for k, v in results.items()}}


def autotune_paged_block(path: Optional[str] = None, num_slots: int = 4,
                         max_new: int = 16) -> dict:
    """Sweep the paged arena block size over a short served stream —
    block size trades table-walk length against gather/DMA granularity
    on every backend (CPU gathers included)."""
    import numpy as np
    import paddle_tpu as paddle
    from ...models.llama import LlamaForCausalLM, llama_tiny_config
    from ...serving import ContinuousBatchingEngine, Server

    paddle.seed(0)
    cfg = llama_tiny_config(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(0)
    prompts = [rs.randint(0, cfg.vocab_size,
                          (6 + (i % 3) * 5,)).astype(np.int32)
               for i in range(num_slots * 2)]
    cands = (8, 16, 32)
    engines = {}

    def run(bs):
        eng = engines.get(bs)
        if eng is None:
            eng = engines[bs] = ContinuousBatchingEngine(
                model, num_slots=num_slots, max_len=64,
                decode_block=4, paged=True, block_size=bs,
                prefill_chunk=bs)
        eng.reset()
        srv = Server(eng)
        for p in prompts:
            srv.submit(p, max_new_tokens=max_new)
        srv.run_until_idle()

    winner, ms, results = _time_best(cands, run, reps=2)
    key = {"knob": "block_size"}
    record("paged_attention", key, {"block_size": winner}, ms,
           path=path, candidates=len(cands))
    return {"kernel": "paged_attention", "key": key,
            "winner": {"block_size": winner},
            "ms": {str(k): round(v, 2) for k, v in results.items()}}


def autotune_flash(seq: int = 1024, heads: int = 8, dim: int = 128,
                   path: Optional[str] = None) -> dict:
    """Sweep splash/flash block preferences on the REAL kernels — TPU
    only (off-TPU the kernels never dispatch, so there is nothing
    honest to time; recorded as skipped)."""
    import jax
    if jax.default_backend() != "tpu":
        return {"kernel": "flash", "skipped": "needs a TPU backend — "
                "the Pallas kernels do not dispatch off-TPU"}
    import jax.numpy as jnp
    import numpy as np
    from . import flash_attention as fa

    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(2, seq, heads, dim).astype(np.float32))
    cands = (128, 256, 512, 1024)

    # no cache clearing needed: each candidate's BlockSizes produce a
    # DISTINCT compiled kernel, so the warm call compiles it and the
    # timed reps measure runtime, not compilation
    def run(blk):
        os.environ["PT_SPLASH_BLOCK"] = str(blk)
        os.environ["PT_JAX_FLASH_BLOCK"] = str(blk)
        try:
            out = fa.sdpa(q, q, q, is_causal=True)
            jax.block_until_ready(out)
        finally:
            os.environ.pop("PT_SPLASH_BLOCK", None)
            os.environ.pop("PT_JAX_FLASH_BLOCK", None)

    winner, ms, results = _time_best(cands, run, reps=2)
    key = {"seq": seq, "dim": dim}
    record("flash_attention", key, {"block_q": winner, "block_kv": winner},
           ms, path=path, candidates=len(cands))
    return {"kernel": "flash_attention", "key": key,
            "winner": {"block_q": winner, "block_kv": winner},
            "ms": {str(k): round(v, 2) for k, v in results.items()}}


def run_autotune(path: Optional[str] = None, rows: int = 256,
                 vocab: int = 8192) -> dict:
    """The bench 'autotune' stage: run every sweep that is honest on
    this backend, persist the stamped table, and PROVE a kernel reads
    it at trace time (the xent chunk cap is re-derived through the
    production lookup path and compared against the recorded
    winner)."""
    path = path or table_path()
    out = {"autotune_table": path}
    xent_res = autotune_xent(rows=rows, vocab=vocab, path=path)
    out["autotune_xent"] = xent_res
    out["autotune_paged"] = autotune_paged_block(path=path)
    out["autotune_flash"] = autotune_flash(path=path)
    table = load_table(path)
    out["autotune_stamp"] = table.get("stamp") if table else None
    out["autotune_entries"] = len(table["entries"]) if table else 0
    # proof of trace-time consumption: the production helper must now
    # return the tuned cap, not the hard-coded default
    from .xent import _tuned_chunk_cap
    got = _tuned_chunk_cap(vocab)
    out["autotune_xent_consulted"] = (
        got == xent_res["winner"]["chunk_cap"])
    out["autotune_paged_default_consulted"] = (
        tuned_paged_block_size()
        == out["autotune_paged"]["winner"]["block_size"])
    return out
