"""MoE token dispatch/combine as pure row-gathers (+ Pallas gather kernel).

Reference parity: the reference routes MoE tokens with dedicated CUDA
collective ops — global_scatter / global_gather
(paddle/fluid/operators/collective/global_scatter_op.* — verify) plus
host-side capacity binning in incubate/distributed/models/moe.

TPU-native design (SURVEY §7 MoE mapping): XLA lowers `buf.at[idx].set`
to scatter HLO, which serializes on TPU, and the autodiff transpose of a
gather is again a scatter-add — so a scatter-based dispatch pays the slow
path in BOTH directions. Instead the router (moe.py `route`) produces the
two index maps

    slot : (T*k,)   token-major -> flat expert-buffer slot (sentinel E*cap
                    for capacity-dropped tokens)
    inv  : (E*cap,) expert-major slot -> flat token*k+j     (sentinel T*k
                    for unfilled slots)

and with both maps every data movement in the MoE layer — dispatch
forward, dispatch backward, combine forward, combine backward (both
cotangents) — is a row-GATHER with out-of-range masking. No scatter
appears anywhere in the compiled step.

The gather itself has two implementations, selectable via
``PT_MOE_GATHER`` (jnp | pallas; A/B'd on chip by moe_breakdown.py):
  - "jnp":    clip-take-mask; XLA emits a dynamic-gather.
  - "pallas": scalar-prefetch kernel — the row index feeds the BlockSpec
    index_map, so each grid step DMAs exactly the source row HBM->VMEM
    (Mosaic double-buffers the row streams); invalid rows are zeroed
    in-kernel.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.flags import env_int, env_str

__all__ = ["gather_rows", "moe_dispatch", "moe_combine",
           "build_index_maps"]


def build_index_maps(topi, num_expert: int, capacity: int):
    """Build the dual token<->slot index maps from top-k expert choices.

    topi: (T, k) int — expert id per (token, choice). Returns
    (slot, inv, keep):
      slot : (T*k,) flat (token, choice) -> expert-buffer slot, with the
             out-of-range sentinel E*cap for capacity-dropped tokens
      inv  : (E*cap,) expert-buffer slot -> flat token*k+j, with the
             out-of-range sentinel T*k for unfilled slots
      keep : (T*k,) bool — not capacity-dropped
    Pure integer jnp (argsort + searchsorted); call on detached/
    stop-gradient inputs. Single source of truth for the routing math —
    MoELayer.forward and moe_breakdown.py both import it.
    """
    t, k = topi.shape
    e, cap = num_expert, capacity
    n = t * k
    flat_e = topi.reshape(-1)                       # (N,)
    sidx = jnp.argsort(flat_e)                      # stable
    se = flat_e[sidx]
    starts = jnp.searchsorted(se, jnp.arange(e))    # (E,)
    pos_sorted = jnp.arange(n) - starts[se]
    pos = jnp.zeros_like(flat_e).at[sidx].set(pos_sorted)
    keep = pos < cap                                # (N,) bool
    slot = jnp.where(keep, flat_e * cap + pos, e * cap)
    # inverse: slot m = (ee, c) is fed by the (starts[ee]+c)-th entry of
    # the expert-sorted order, when c < count[ee]
    ee = jnp.arange(e * cap) // cap
    c = jnp.arange(e * cap) % cap
    src = starts[ee] + c
    ends = jnp.append(starts[1:], n)
    inv = jnp.where(src < ends[ee], sidx[jnp.clip(src, 0, n - 1)], n)
    return slot.astype(jnp.int32), inv.astype(jnp.int32), keep

# tests set this to run the Pallas kernel in interpret mode on CPU
_FORCE_INTERPRET = False


def _pallas_ok(d: int, dtype) -> bool:
    if _FORCE_INTERPRET:
        return True
    try:
        import jax.experimental.pallas  # noqa: F401
    except Exception:
        return False
    return (jax.default_backend() == "tpu" and d % 128 == 0
            and dtype in (jnp.float32, jnp.bfloat16))


def _gather_impl() -> str:
    return env_str("PT_MOE_GATHER", "jnp")


def _gather_rows_jnp(x, idx):
    t = x.shape[0]
    safe = jnp.clip(idx, 0, t - 1)
    out = jnp.take(x, safe, axis=0)
    valid = ((idx >= 0) & (idx < t))[:, None]
    return jnp.where(valid, out, jnp.zeros((), x.dtype))


def _gather_rows_pallas(x, idx):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t, d = x.shape
    m = idx.shape[0]

    def kernel(idx_ref, x_ref, out_ref):
        i = pl.program_id(0)
        row = idx_ref[i]

        @pl.when((row >= 0) & (row < t))
        def _copy():
            out_ref[...] = x_ref[...]

        @pl.when(~((row >= 0) & (row < t)))
        def _zero():
            out_ref[...] = jnp.zeros_like(out_ref)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m,),
        in_specs=[pl.BlockSpec(
            (1, d), lambda i, idx_ref: (jnp.clip(idx_ref[i], 0, t - 1), 0))],
        out_specs=pl.BlockSpec((1, d), lambda i, idx_ref: (i, 0)),
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m, d), x.dtype),
        interpret=_FORCE_INTERPRET,
    )(idx.astype(jnp.int32), x)


def _gather_rows_pallas_mr(x, idx, rows_per_step: int = 8):
    """Multi-row gather: R async row-DMAs per grid step (VERDICT r4
    weak #3's tuning lever for the (1, d) kernel).

    The (1, d) kernel leans on Mosaic double-buffering one row stream;
    if the per-row DMA doesn't pipeline, grid-step overhead dominates.
    Here each grid step issues R independent HBM->VMEM row copies
    (per-slot DMA semaphores), waits once, then zeroes the invalid
    rows — R× fewer grid steps and R DMAs in flight by construction.
    ``PT_MOE_GATHER=pallas_mr`` selects it; ``PT_MOE_GATHER_ROWS``
    tunes R. A/B'd against jnp + (1, d) pallas by moe_breakdown.py.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t, d = x.shape
    m = idx.shape[0]
    r_step = max(1, rows_per_step)
    m_pad = ((m + r_step - 1) // r_step) * r_step
    idx_p = idx.astype(jnp.int32)
    if m_pad != m:
        idx_p = jnp.concatenate(
            [idx_p, jnp.full((m_pad - m,), -1, jnp.int32)])

    def kernel(idx_ref, x_ref, out_ref, sems):
        step = pl.program_id(0)
        for r in range(r_step):              # static unroll
            row = idx_ref[step * r_step + r]
            safe = jnp.clip(row, 0, t - 1)
            pltpu.make_async_copy(
                x_ref.at[pl.ds(safe, 1), :],
                out_ref.at[pl.ds(r, 1), :],
                sems.at[r],
            ).start()
        for r in range(r_step):
            row = idx_ref[step * r_step + r]
            safe = jnp.clip(row, 0, t - 1)
            pltpu.make_async_copy(
                x_ref.at[pl.ds(safe, 1), :],
                out_ref.at[pl.ds(r, 1), :],
                sems.at[r],
            ).wait()
        for r in range(r_step):
            row = idx_ref[step * r_step + r]

            @pl.when(~((row >= 0) & (row < t)))
            def _zero(r=r):
                out_ref[pl.ds(r, 1), :] = jnp.zeros((1, d), x.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m_pad // r_step,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.MemorySpace.ANY)],
        out_specs=pl.BlockSpec((r_step, d), lambda i, idx_ref: (i, 0)),
        scratch_shapes=[pltpu.SemaphoreType.DMA((r_step,))],
    )
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m_pad, d), x.dtype),
        interpret=_FORCE_INTERPRET,
    )(idx_p, x)
    return out[:m] if m_pad != m else out


def gather_rows(x, idx):
    """out[i] = x[idx[i]] for in-range idx, else zeros. (rows, d) gather."""
    impl = _gather_impl()
    if impl == "pallas" and _pallas_ok(x.shape[-1], x.dtype):
        return _gather_rows_pallas(x, idx)
    if impl == "pallas_mr" and _pallas_ok(x.shape[-1], x.dtype):
        return _gather_rows_pallas_mr(
            x, idx, env_int("PT_MOE_GATHER_ROWS", 8))
    return _gather_rows_jnp(x, idx)


def _f0(a):
    return np.zeros(a.shape, jax.dtypes.float0)


# ---------------------------------------------------------------- dispatch

@jax.custom_vjp
def moe_dispatch(x, inv, slot):
    """(T, d) tokens -> (E*cap, d) expert-major buffer, all-gather form.

    ``inv // k`` maps a slot to its source token; the sentinel T*k divides
    to T which gather_rows masks to zeros (an unfilled capacity slot).
    """
    k = slot.shape[0] // x.shape[0]
    return gather_rows(x, inv // k)


def _dispatch_fwd(x, inv, slot):
    return moe_dispatch(x, inv, slot), (x.shape[0], inv, slot)


def _dispatch_bwd(res, dbuf):
    t, inv, slot = res
    k = slot.shape[0] // t
    # dx[t] = sum_j dbuf[slot[t, j]]; dropped tokens hit the E*cap
    # sentinel, which gathers as zeros — their gradient contribution is
    # correctly nothing
    dx = gather_rows(dbuf, slot).reshape(t, k, -1).sum(axis=1)
    return dx, _f0(inv), _f0(slot)


moe_dispatch.defvjp(_dispatch_fwd, _dispatch_bwd)


# ----------------------------------------------------------------- combine

@jax.custom_vjp
def moe_combine(flat, gates, inv, slot):
    """(E*cap, d) expert outputs + (T, k) gate weights -> (T, d)."""
    t, k = gates.shape
    rows = gather_rows(flat, slot).reshape(t, k, -1)
    return (rows * gates[..., None].astype(flat.dtype)).sum(axis=1)


def _combine_fwd(flat, gates, inv, slot):
    return moe_combine(flat, gates, inv, slot), (flat, gates, inv, slot)


def _combine_bwd(res, dout):
    flat, gates, inv, slot = res
    t, k = gates.shape
    n = t * k
    # d flat[m] = gates[inv[m]] * dout[token(m)] — expert-major gather
    gates_flat = gates.reshape(n)
    gval = jnp.where(inv < n, jnp.take(gates_flat,
                                       jnp.clip(inv, 0, n - 1)), 0.0)
    dflat = (gval[:, None].astype(dout.dtype)
             * gather_rows(dout, inv // k)).astype(flat.dtype)
    # d gates[t, j] = <dout[t], flat[slot[t, j]]> — recompute the row
    # gather instead of saving the (T, k, d) rows tensor (memory-lean,
    # one extra bandwidth pass, mirroring flash-style recompute)
    rows = gather_rows(flat, slot).reshape(t, k, -1)
    dgates = (rows.astype(dout.dtype) * dout[:, None, :]).sum(axis=-1)
    return dflat, dgates.astype(gates.dtype), _f0(inv), _f0(slot)


moe_combine.defvjp(_combine_fwd, _combine_bwd)
