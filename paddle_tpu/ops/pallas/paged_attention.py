"""Paged-attention decode kernel over a block-paged KV arena.

The serving engine's paged mode (serving/paging.py) stores every slot's
KV history as fixed-size blocks inside one shared
``(num_blocks, block_size, kv_heads, head_dim)`` arena; a per-slot
block table maps the slot's timeline block j to an arena block id
(vLLM's PagedAttention layout restated under the repo's static-shape
rules — block 0 is the reserved trash block dead slots write into).

TPU-native design: the kernel runs one grid step per (slot, table
entry); the block table and per-slot lengths ride as SCALAR-PREFETCH
operands so the k/v BlockSpec index_map can address the arena block
directly — the gather IS the DMA schedule, no (S, max_len) dense view
ever materializes. Attention over the blocks is an online softmax
(running max / normalizer / accumulator in VMEM scratch, finalized on
the last table entry), with table entries past the slot's length
skipped via ``pl.when``. Off-TPU (and in the CPU quick lane) the SAME
call falls back to :func:`paged_attention_reference` — a gather of the
table into the dense layout followed by exactly the einsum/mask/softmax
sequence of ``models.generation.cached_attention``, which is what keeps
paged greedy streams bit-identical to the dense engine.

int8 KV mode reuses the EQuARX wire-format helpers from
``distributed/collectives/quantized.py``: codes quantized per
(position, kv-head) vector against its absmax (the "bucket" is the
head_dim vector), dequantized to fp32 at read. Single quantization, no
reduce, so the documented bound specializes to
``absmax / 127 / 2`` elementwise (:func:`kv_int8_error_bound` derives
it from ``int8_error_bound`` with n=1 and no phase-2 term).

Bandwidth-true int8 decode (:func:`paged_attention_decode_int8`): the
dequantization happens INSIDE the read, never ahead of it. On TPU the
int8 kernel DMAs code blocks plus their ``(block_size, kv_heads)``
scale blocks through the same scalar-prefetch index_map and dequantizes
each block in registers — HBM sees ~(1 + 4/d)-byte/element traffic, the
actual quantized footprint. Off-TPU the fallback is a ``lax.scan`` over
table entries that gathers ONE block of codes+scales at a time,
dequantizes it, and folds it into the same online softmax — so even the
CPU jaxpr holds no fp32 KV transient beyond a single
``(b, block_size, kvh, d)`` block (asserted by a recursive jaxpr walk
in tests/test_serving_quant.py). The dequant-then-dense formulation
survives only as :func:`paged_attention_int8_reference`, the test
oracle the in-read paths are pinned against.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import fused as _fused

__all__ = ["paged_attention_decode", "paged_attention_decode_int8",
           "paged_attention_reference", "paged_attention_int8_reference",
           "paged_gather", "quantize_kv", "dequantize_kv",
           "kv_int8_error_bound"]

_NEG = -1e30

# tests flip this to route the s=1 int8 read through the
# dequant-then-dense oracle instead of the in-read path — the lever the
# production-vs-oracle greedy-stream parity pin uses
_FORCE_INT8_REFERENCE = False


# ---------------------------------------------------------------------------
# int8 KV wire format (EQuARX helpers, head_dim-vector buckets)
# ---------------------------------------------------------------------------

def quantize_kv(x):
    """(..., d) fp32-ish -> ((..., d) int8 codes, (...,) fp32 absmax
    scales): one EQuARX bucket per (position, kv-head) vector."""
    from ...distributed.collectives.quantized import _quantize
    d = x.shape[-1]
    codes, scales = _quantize(x.astype(jnp.float32).reshape(-1), d)
    return (codes.reshape(x.shape),
            scales.reshape(x.shape[:-1]))


def dequantize_kv(codes, scales):
    """Inverse of :func:`quantize_kv` (fp32 out; the ±127 codes
    reproduce ±absmax bit-exactly, so constant vectors round-trip)."""
    from ...distributed.collectives.quantized import _dequantize
    d = codes.shape[-1]
    return _dequantize(codes.reshape(-1, d),
                       scales.reshape(-1)).reshape(codes.shape)


def _deq_block(codes, scales):
    """Register-level EQuARX dequant of ONE block: codes (..., d) int8,
    scales (...,) fp32 -> fp32. THE collectives formula (±127 codes
    reproduce ±absmax bit-exactly), not a restatement — the Pallas
    kernel, the scan fallback and quantize_kv/dequantize_kv can never
    drift apart."""
    from ...distributed.collectives.quantized import _dequantize
    return _dequantize(codes, scales)


def kv_int8_error_bound(absmax):
    """Worst-case elementwise |dequant - fp32| for the int8 KV cache:
    a single quantization (n=1 contributor, no re-quantized phase 2)
    of the documented collectives contract — absmax / 127 / 2."""
    from ...distributed.collectives.quantized import int8_error_bound
    return int8_error_bound(absmax, 1,
                            bucket_absmax_out=jnp.zeros_like(
                                jnp.asarray(absmax, jnp.float32)))


# ---------------------------------------------------------------------------
# reference path: block-table gather + the dense attention sequence
# ---------------------------------------------------------------------------

def paged_gather(arena, block_table):
    """(nb, bs, kvh, d) arena + (b, max_blocks) table -> the slot-dense
    (b, max_blocks*bs, kvh, d) view ordered by timeline position."""
    b, mb = block_table.shape
    g = arena[block_table]                     # (b, mb, bs, kvh, d)
    return g.reshape(b, mb * g.shape[2], *g.shape[3:])


def _dense_attention(q, kd, vd, lengths, *, scale, window=None):
    """The dense einsum/mask/softmax sequence over already-gathered
    (b, T, kvh, d) k/v — bit-identical math to the dense engine. ``q``
    is (b, s, h, d); q_idx = lengths - s + i."""
    b, s, h, d = q.shape
    kvh = kd.shape[2]
    g = h // kvh
    qg = q.reshape(b, s, kvh, g, d).astype(jnp.float32)
    scores = jnp.einsum("bskgd,btkd->bkgst", qg,
                        kd.astype(jnp.float32)) * scale
    t_idx = jnp.arange(kd.shape[1])
    q_idx = (lengths - s)[:, None] + jnp.arange(s)[None, :]   # (b, s)
    mask = t_idx[None, None, :] <= q_idx[:, :, None]
    if window is not None:
        mask = mask & (t_idx[None, None, :]
                       > q_idx[:, :, None] - int(window))
    scores = jnp.where(mask[:, None, None], scores, jnp.float32(_NEG))
    probs = jax.nn.softmax(scores, axis=-1).astype(vd.dtype)
    out = jnp.einsum("bkgst,btkd->bskgd", probs, vd)
    return out.reshape(b, s, h, d).astype(q.dtype)


def paged_attention_reference(q, k_arena, v_arena, block_table, lengths,
                              *, scale, window=None):
    """Gathered-dense oracle: bit-identical math to the dense engine
    (same einsums, same -1e30 mask, same fp32 softmax). ``q`` is
    (b, s, h, d) — s=1 decode or an s-token prefill chunk whose rows
    end at ``lengths`` (q_idx = lengths - s + i)."""
    kd = paged_gather(k_arena, block_table)
    vd = paged_gather(v_arena, block_table)
    return _dense_attention(q, kd, vd, lengths, scale=scale,
                            window=window)


def paged_attention_int8_reference(q, k_codes, v_codes, k_scales,
                                   v_scales, block_table, lengths, *,
                                   scale, window=None):
    """Dequant-then-dense TEST ORACLE for the int8 arena: gather the
    whole table, dequantize into the dense fp32 layout, run the dense
    attention sequence. This is the very transient the in-read paths
    exist to eliminate — it lives on only to pin their numerics."""
    kd = dequantize_kv(paged_gather(k_codes, block_table),
                       paged_gather(k_scales, block_table))
    vd = dequantize_kv(paged_gather(v_codes, block_table),
                       paged_gather(v_scales, block_table))
    return _dense_attention(q, kd, vd, lengths, scale=scale,
                            window=window)


# ---------------------------------------------------------------------------
# Pallas kernel: decode (s=1), block-table scalar prefetch
# ---------------------------------------------------------------------------

def _online_update(q, k, v, j, bs, length, scale, m_ref, l_ref, acc_ref):
    """Fold one fp32 (bs, kvh, d) KV block into the running online
    softmax (max / normalizer / accumulator scratch refs). Shared by
    the fp32 and int8 kernels — the int8 kernel differs ONLY in how k/v
    reach fp32."""
    kvh = k.shape[1]
    h, d = q.shape
    qg = q.reshape(kvh, h // kvh, d)
    s = jnp.einsum("kgd,tkd->kgt", qg, k) * scale   # (kvh, g, bs)
    t = j * bs + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
    s = jnp.where(t < length, s, _NEG)
    m_prev, l_prev = m_ref[...], l_ref[...]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m_prev - m_new)
    l_ref[...] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * corr + jnp.einsum("kgt,tkd->kgd", p, v)
    m_ref[...] = m_new


def _decode_kernel_core(len_ref, q_ref, read_kv, o_ref, m_ref, l_ref,
                        acc_ref, *, bs, scale, nblocks):
    """ONE online-softmax scratch lifecycle (init at j==0, per-block
    fold, finalize at the last table entry) shared by the fp32 and
    int8 kernels — they differ ONLY in ``read_kv``, how the current
    block's k/v reach fp32."""
    from jax.experimental import pallas as pl
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    length = len_ref[i]

    @pl.when(j * bs < length)
    def _block():
        k, v = read_kv()
        _online_update(q_ref[0].astype(jnp.float32), k, v,
                       j, bs, length, scale, m_ref, l_ref, acc_ref)

    @pl.when(j == nblocks - 1)
    def _finalize():
        kvh, g, d = acc_ref.shape
        o_ref[0] = (acc_ref[...] / l_ref[...]).reshape(
            kvh * g, d).astype(o_ref.dtype)


def _decode_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, bs, scale, nblocks):
    _decode_kernel_core(
        len_ref, q_ref,
        lambda: (k_ref[0].astype(jnp.float32),
                 v_ref[0].astype(jnp.float32)),
        o_ref, m_ref, l_ref, acc_ref, bs=bs, scale=scale,
        nblocks=nblocks)


def _decode_kernel_int8(tbl_ref, len_ref, q_ref, k_ref, v_ref, sk_ref,
                        sv_ref, o_ref, m_ref, l_ref, acc_ref, *, bs,
                        scale, nblocks):
    """int8 twin of :func:`_decode_kernel`: the k/v blocks arrive as
    int8 codes plus their (bs, kvh) fp32 absmax scale blocks (same
    scalar-prefetch index_map — the scale DMA rides the code DMA), and
    the dequant happens in registers right before the block's einsum.
    HBM traffic per table entry is the quantized footprint."""
    _decode_kernel_core(
        len_ref, q_ref,
        lambda: (_deq_block(k_ref[0], sk_ref[0]),
                 _deq_block(v_ref[0], sv_ref[0])),
        o_ref, m_ref, l_ref, acc_ref, bs=bs, scale=scale,
        nblocks=nblocks)


def _kernel_ok(k_arena) -> bool:
    """Route the s=1 fp32/bf16 read through the Pallas kernel (real TPU
    or forced interpret mode); everything else takes the gathered-dense
    reference path — including the whole CPU quick lane, which is what
    keeps paged streams bit-identical to the dense engine there."""
    return (k_arena.dtype in (jnp.float32, jnp.bfloat16)
            and _fused._pallas_ok())


def _kernel_ok_int8(k_codes) -> bool:
    """The int8 kernel's routing gate: code arenas only, TPU or forced
    interpret mode. Off-TPU the int8 read takes the per-block scan
    fallback (NOT the dense oracle — the no-fp32-KV-transient contract
    holds on every backend)."""
    return k_codes.dtype == jnp.int8 and _fused._pallas_ok()


def _grid_call(kernel, in_specs, operands, b, mb, h, d, kvh, out_dtype):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, d),
                               lambda i, j, tbl, lens: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((kvh, h // kvh, 1), jnp.float32),
            pltpu.VMEM((kvh, h // kvh, 1), jnp.float32),
            pltpu.VMEM((kvh, h // kvh, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), out_dtype),
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_fused._FORCE_INTERPRET,
    )(*operands)


def paged_attention_decode(q, k_arena, v_arena, block_table, lengths,
                           *, scale):
    """One decode step of paged attention: q (b, h, d) against the
    arena through the block table; lengths (b,) = tokens valid per slot
    (the just-written current token included). Online softmax over the
    table entries; entries past the length are skipped, entry 0 (trash)
    is only ever touched by skipped/dead rows."""
    from jax.experimental import pallas as pl

    b, h, d = q.shape
    nb, bs, kvh, _ = k_arena.shape
    mb = block_table.shape[1]
    in_specs = [
        pl.BlockSpec((1, h, d), lambda i, j, tbl, lens: (i, 0, 0)),
        pl.BlockSpec((1, bs, kvh, d),
                     lambda i, j, tbl, lens: (tbl[i, j], 0, 0, 0)),
        pl.BlockSpec((1, bs, kvh, d),
                     lambda i, j, tbl, lens: (tbl[i, j], 0, 0, 0)),
    ]
    return _grid_call(
        functools.partial(_decode_kernel, bs=bs, scale=scale,
                          nblocks=mb),
        in_specs, (block_table, lengths, q, k_arena, v_arena),
        b, mb, h, d, kvh, q.dtype)


def _int8_decode_fallback(q, k_codes, v_codes, k_scales, v_scales,
                          block_table, lengths, *, scale):
    """Off-TPU mirror of the int8 kernel: ``lax.scan`` over table
    entries, gathering and dequantizing ONE (b, bs, kvh, d) block per
    step into the same online softmax. The largest fp32 KV value alive
    at any point is a single block — the dense (b, T, kvh, d) transient
    of the old dequant-then-gather path never exists (jaxpr-walk
    pinned)."""
    b, h, d = q.shape
    nb, bs, kvh, _ = k_codes.shape
    mb = block_table.shape[1]
    g = h // kvh
    qg = q.reshape(b, kvh, g, d).astype(jnp.float32)

    def body(carry, j):
        m, l, acc = carry
        blk = block_table[:, j]                        # (b,)
        k = _deq_block(k_codes[blk], k_scales[blk])    # (b, bs, kvh, d)
        v = _deq_block(v_codes[blk], v_scales[blk])
        s = jnp.einsum("bkgd,btkd->bkgt", qg, k) * scale
        t = j * bs + jnp.arange(bs)
        s = jnp.where(t[None, None, None, :]
                      < lengths[:, None, None, None], s,
                      jnp.float32(_NEG))
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum("bkgt,btkd->bkgd", p, v)
        return (m_new, l, acc), None

    m0 = jnp.full((b, kvh, g, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((b, kvh, g, 1), jnp.float32)
    acc0 = jnp.zeros((b, kvh, g, d), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(body, (m0, l0, acc0),
                                  jnp.arange(mb, dtype=jnp.int32))
    out = (acc / l).reshape(b, h, d)
    return out.astype(q.dtype)


def paged_attention_decode_int8(q, k_codes, v_codes, k_scales, v_scales,
                                block_table, lengths, *, scale):
    """One decode step against the int8 arena with the dequant INSIDE
    the read: the Pallas int8 kernel on TPU/interpret, the per-block
    scan fallback everywhere else. Numerics: identical quantized inputs
    and fp32 accumulation as the dequant-then-dense oracle, reassociated
    by the online softmax — parity is pinned to ~1e-5, and greedy
    engine streams are pinned token-identical to the oracle route."""
    from jax.experimental import pallas as pl

    if _FORCE_INT8_REFERENCE:
        return paged_attention_int8_reference(
            q[:, None], k_codes, v_codes, k_scales, v_scales,
            block_table, lengths, scale=scale)[:, 0]
    if not _kernel_ok_int8(k_codes):
        return _int8_decode_fallback(
            q, k_codes, v_codes, k_scales, v_scales, block_table,
            lengths, scale=scale)
    b, h, d = q.shape
    nb, bs, kvh, _ = k_codes.shape
    mb = block_table.shape[1]
    in_specs = [
        pl.BlockSpec((1, h, d), lambda i, j, tbl, lens: (i, 0, 0)),
        pl.BlockSpec((1, bs, kvh, d),
                     lambda i, j, tbl, lens: (tbl[i, j], 0, 0, 0)),
        pl.BlockSpec((1, bs, kvh, d),
                     lambda i, j, tbl, lens: (tbl[i, j], 0, 0, 0)),
        pl.BlockSpec((1, bs, kvh),
                     lambda i, j, tbl, lens: (tbl[i, j], 0, 0)),
        pl.BlockSpec((1, bs, kvh),
                     lambda i, j, tbl, lens: (tbl[i, j], 0, 0)),
    ]
    return _grid_call(
        functools.partial(_decode_kernel_int8, bs=bs, scale=scale,
                          nblocks=mb),
        in_specs, (block_table, lengths, q, k_codes, v_codes,
                   k_scales, v_scales),
        b, mb, h, d, kvh, q.dtype)
