"""Fused Pallas TPU kernels for the transformer hot path.

Reference parity: the reference ships fused CUDA kernels for exactly
these ops — fused_rms_norm / rms_norm_kernel, fused_rope,
adamw multi-tensor kernel (paddle/phi/kernels/fusion/gpu/,
paddle/phi/kernels/gpu/adamw_kernel.cu — verify).

TPU-native design: each kernel is one pass HBM->VMEM->HBM tiled to the
VPU (8x128 lanes): RMSNorm fuses residual-add + normalize + scale;
RoPE rotates q and k in one launch; AdamW updates param + both moments
in a single read-modify-write per block (the win over XLA's default is
fewer HBM round-trips when the optimizer update is not fused into the
step program). Every entry point has an identical-math jnp fallback
(used off-TPU and as the custom-vjp backward), so numerics are testable
on CPU via interpret mode.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


# tests set this to run the Pallas kernels in interpret mode on CPU so
# the kernel code itself is exercised without TPU hardware
_FORCE_INTERPRET = False


def _pallas_ok() -> bool:
    if _FORCE_INTERPRET:
        return True
    try:
        import jax.experimental.pallas  # noqa: F401
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _round_up(n, m):
    return (n + m - 1) // m * m


# ---------------------------------------------------------------------------
# fused RMSNorm (+ residual)
# ---------------------------------------------------------------------------

def _rms_ref(x, weight, eps, residual):
    if residual is not None:
        x = x + residual
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    out = (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight
    return (out, x) if residual is not None else out


def _rms_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    o_ref[...] = (x * jax.lax.rsqrt(var + eps)).astype(o_ref.dtype) \
        * w_ref[...]


def _rms_res_kernel(x_ref, r_ref, w_ref, o_ref, s_ref, *, eps):
    s = x_ref[...] + r_ref[...]
    s_ref[...] = s
    x = s.astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    o_ref[...] = (x * jax.lax.rsqrt(var + eps)).astype(o_ref.dtype) \
        * w_ref[...]


def _rms_pallas(x, weight, eps, residual):
    from jax.experimental import pallas as pl

    orig_shape = x.shape
    h = orig_shape[-1]
    rows = x.size // h
    x2 = x.reshape(rows, h)
    block_rows = max(8, min(256, _round_up(rows, 8) // 8 * 8))
    grid = (pl.cdiv(rows, block_rows),)
    row_spec = pl.BlockSpec((block_rows, h), lambda i: (i, 0))
    w_spec = pl.BlockSpec((h,), lambda i: (0,))
    if residual is None:
        out = pl.pallas_call(
            functools.partial(_rms_kernel, eps=eps),
            grid=grid,
            in_specs=[row_spec, w_spec],
            out_specs=row_spec,
            out_shape=jax.ShapeDtypeStruct((rows, h), x.dtype),
            interpret=_FORCE_INTERPRET,
        )(x2, weight)
        return out.reshape(orig_shape)
    r2 = residual.reshape(rows, h)
    out, s = pl.pallas_call(
        functools.partial(_rms_res_kernel, eps=eps),
        grid=grid,
        in_specs=[row_spec, row_spec, w_spec],
        out_specs=[row_spec, row_spec],
        out_shape=[jax.ShapeDtypeStruct((rows, h), x.dtype),
                   jax.ShapeDtypeStruct((rows, h), x.dtype)],
        interpret=_FORCE_INTERPRET,
    )(x2, r2, weight)
    return out.reshape(orig_shape), s.reshape(orig_shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fused_rms_norm_core(x, weight, eps):
    if _pallas_ok():
        return _rms_pallas(x, weight, eps, None)
    return _rms_ref(x, weight, eps, None)


def _rms_fwd(x, weight, eps):
    return _fused_rms_norm_core(x, weight, eps), (x, weight)


def _rms_bwd(eps, saved, ct):
    x, weight = saved
    _, vjp = jax.vjp(lambda a, w: _rms_ref(a, w, eps, None), x, weight)
    return vjp(ct)


_fused_rms_norm_core.defvjp(_rms_fwd, _rms_bwd)


def fused_rms_norm(x, weight, eps: float = 1e-6,
                   residual: Optional[jax.Array] = None):
    """RMSNorm, optionally fused with a residual add.

    Without residual: returns normalized(x) * weight.
    With residual: returns (normalized(x + residual) * weight,
    x + residual) — the second output feeds the next skip connection
    (the reference's fused_rms_norm contract).
    """
    if residual is None:
        return _fused_rms_norm_core(x, weight, eps)
    # residual path: differentiable via the reference impl (two outputs);
    # pallas forward when available
    if _pallas_ok():
        @jax.custom_vjp
        def core(x_, r_, w_):
            return _rms_pallas(x_, w_, eps, r_)

        def fwd(x_, r_, w_):
            return core(x_, r_, w_), (x_, r_, w_)

        def bwd(saved, cts):
            x_, r_, w_ = saved
            _, vjp = jax.vjp(
                lambda a, r, w: _rms_ref(a, w, eps, r), x_, r_, w_)
            return vjp(cts)

        core.defvjp(fwd, bwd)
        return core(x, residual, weight)
    return _rms_ref(x, weight, eps, residual)


def layer_norm_one_pass(x, eps: float, axes=(-1,)):
    """Normalize over ``axes`` with fp32 accumulation, reading x ONCE:
    shifted one-pass moments var = E[(x-s)^2] - E[x-s]^2 with s the
    per-row first element. The shift kills the catastrophic
    cancellation the textbook E[x^2]-E[x]^2 form hits when |mean| >>
    std (measured: 8.8e2 max err at offset 1e4 unshifted vs 5.6e-4
    shifted); the output is shift-invariant so stop_gradient(s) is
    exact. Shared by nn.functional.layer_norm and the fusion pass's
    layer_norm rewrite — fix numerics HERE, once."""
    axes = tuple(a % x.ndim for a in axes)
    xf = x.astype(jnp.float32)
    idx = tuple(slice(0, 1) if a in axes else slice(None)
                for a in range(x.ndim))
    shift = jax.lax.stop_gradient(xf[idx])
    d = xf - shift
    dm = jnp.mean(d, axis=axes, keepdims=True)
    d2 = jnp.mean(d * d, axis=axes, keepdims=True)
    var = jnp.maximum(d2 - dm * dm, 0.0)
    return ((d - dm) * jax.lax.rsqrt(var + eps)).astype(x.dtype)


# ---------------------------------------------------------------------------
# fused rotary position embedding
# ---------------------------------------------------------------------------

def _rope_ref(q, k, cos, sin):
    """(b, s, h, d) with cos/sin (s, d) — rotate-half convention."""
    def rot(x):
        x1, x2 = jnp.split(x, 2, axis=-1)
        return jnp.concatenate([-x2, x1], axis=-1)

    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    return q * c + rot(q) * s, k * c + rot(k) * s


def _rope_kernel(q_ref, k_ref, c_ref, s_ref, oq_ref, ok_ref):
    c = c_ref[...]                   # (rows, 1, d): broadcasts over heads
    s = s_ref[...]

    def rot(x):
        half = x.shape[-1] // 2
        x1 = x[..., :half]
        x2 = x[..., half:]
        return jnp.concatenate([-x2, x1], axis=-1)

    q = q_ref[...]                   # (rows, h, d)
    k = k_ref[...]
    oq_ref[...] = q * c + rot(q) * s
    ok_ref[...] = k * c + rot(k) * s


def _rope_pallas(q, k, cos, sin):
    from jax.experimental import pallas as pl

    b, sq, h, d = q.shape
    # flatten to (b*s, h, d): Pallas TPU requires the last TWO block dims
    # aligned (8, 128) or equal to the array dims — (h, d) are kept whole,
    # the row dim is the grid. cos/sin are pre-broadcast over the batch so
    # each row block reads matching angles.
    rows = b * sq
    q3 = q.reshape(rows, h, d)
    k3 = k.reshape(rows, h, d)
    # (rows, 1, d): already rank-3 so the kernel never reshapes (Mosaic
    # cannot shape-cast vectors), middle dim broadcasts over heads
    c2 = jnp.broadcast_to(cos[None], (b, sq, d)).reshape(rows, 1, d)
    s2 = jnp.broadcast_to(sin[None], (b, sq, d)).reshape(rows, 1, d)
    # ~1MB blocks: 256 * h * d * 4B at (h=16, d=64); 4 tensors in flight
    rb = rows if rows <= 256 else 256
    grid = (pl.cdiv(rows, rb),)
    qspec = pl.BlockSpec((rb, h, d), lambda i: (i, 0, 0))
    cspec = pl.BlockSpec((rb, 1, d), lambda i: (i, 0, 0))
    oq, ok = pl.pallas_call(
        _rope_kernel,
        grid=grid,
        in_specs=[qspec, qspec, cspec, cspec],
        out_specs=[qspec, qspec],
        out_shape=[jax.ShapeDtypeStruct((rows, h, d), q.dtype),
                   jax.ShapeDtypeStruct((rows, h, d), k.dtype)],
        interpret=_FORCE_INTERPRET,
    )(q3, k3, c2, s2)
    return oq.reshape(q.shape), ok.reshape(k.shape)


@jax.custom_vjp
def fused_rope(q, k, cos, sin):
    """Apply rotary embeddings to q and k in one fused launch.
    q, k: (b, s, h, d); cos, sin: (s, d). GQA (fewer kv heads) runs as
    two launches — rope is per-head, so the kernel is reused per
    tensor."""
    if _pallas_ok():
        if q.shape == k.shape:
            return _rope_pallas(q, k, cos, sin)
        oq, _ = _rope_pallas(q, q, cos, sin)
        ok, _ = _rope_pallas(k, k, cos, sin)
        return oq, ok
    return _rope_ref(q, k, cos, sin)


def _rope_fwd(q, k, cos, sin):
    return fused_rope(q, k, cos, sin), (cos, sin)


def _rope_bwd(saved, cts):
    cos, sin = saved
    ctq, ctk = cts

    # rotation is orthogonal: the vjp is rotation by -theta
    def unrot(ct):
        def rot_inv(x):
            x1, x2 = jnp.split(x, 2, axis=-1)
            return jnp.concatenate([x2, -x1], axis=-1)
        c = cos[None, :, None, :]
        s = sin[None, :, None, :]
        return ct * c + rot_inv(ct) * s

    return unrot(ctq), unrot(ctk), None, None


fused_rope.defvjp(_rope_fwd, _rope_bwd)


# ---------------------------------------------------------------------------
# fused AdamW update
# ---------------------------------------------------------------------------

def _adamw_ref(p, g, m, v, lr, beta1, beta2, eps, weight_decay, step):
    m_new = beta1 * m + (1 - beta1) * g
    v_new = beta2 * v + (1 - beta2) * g * g
    mhat = m_new / (1 - beta1 ** step)
    vhat = v_new / (1 - beta2 ** step)
    p_new = p - lr * (mhat / (jnp.sqrt(vhat) + eps) + weight_decay * p)
    return p_new, m_new, v_new


def _adamw_kernel(p_ref, g_ref, m_ref, v_ref, sc_ref,
                  po_ref, mo_ref, vo_ref):
    lr = sc_ref[0]
    beta1 = sc_ref[1]
    beta2 = sc_ref[2]
    eps = sc_ref[3]
    wd = sc_ref[4]
    bc1 = sc_ref[5]     # 1 - beta1**step
    bc2 = sc_ref[6]     # 1 - beta2**step
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    m = m_ref[...]
    v = v_ref[...]
    m_new = beta1 * m + (1 - beta1) * g
    v_new = beta2 * v + (1 - beta2) * g * g
    mhat = m_new / bc1
    vhat = v_new / bc2
    p_new = p - lr * (mhat / (jnp.sqrt(vhat) + eps) + wd * p)
    po_ref[...] = p_new.astype(po_ref.dtype)
    mo_ref[...] = m_new
    vo_ref[...] = v_new


def fused_adamw(p, g, m, v, lr, beta1=0.9, beta2=0.999, eps=1e-8,
                weight_decay=0.01, step=1):
    """One-pass AdamW: reads p/g/m/v once, writes p/m/v once.
    m and v are float32 master moments; p may be bf16."""
    if not _pallas_ok() or p.size < 1024:
        return _adamw_ref(p, g, m, v, lr, beta1, beta2, eps,
                          weight_decay, step)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = p.size
    lanes = 128
    rows = pl.cdiv(n, lanes)
    pad = rows * lanes - n

    def flat(x, dt):
        x = x.reshape(-1).astype(dt)
        if pad:
            x = jnp.pad(x, (0, pad))
        return x.reshape(rows, lanes)

    scalars = jnp.asarray(
        [lr, beta1, beta2, eps, weight_decay,
         1 - beta1 ** step, 1 - beta2 ** step], jnp.float32)
    block_rows = min(512, _round_up(rows, 8))
    grid = (pl.cdiv(rows, block_rows),)
    spec = pl.BlockSpec((block_rows, lanes), lambda i: (i, 0))
    sspec = pl.BlockSpec(memory_space=pltpu.SMEM)
    po, mo, vo = pl.pallas_call(
        _adamw_kernel,
        grid=grid,
        in_specs=[spec, spec, spec, spec, sspec],
        out_specs=[spec, spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows, lanes), p.dtype),
            jax.ShapeDtypeStruct((rows, lanes), jnp.float32),
            jax.ShapeDtypeStruct((rows, lanes), jnp.float32),
        ],
        interpret=_FORCE_INTERPRET,
    )(flat(p, p.dtype), flat(g, jnp.float32), flat(m, jnp.float32),
      flat(v, jnp.float32), scalars)

    def unflat(x, shape, dt):
        return x.reshape(-1)[:n].reshape(shape).astype(dt)

    return (unflat(po, p.shape, p.dtype),
            unflat(mo, m.shape, jnp.float32),
            unflat(vo, v.shape, jnp.float32))
