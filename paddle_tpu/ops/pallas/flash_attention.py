"""Flash attention for TPU.

Reference parity: phi FlashAttnKernel (reference:
paddle/phi/kernels/gpu/flash_attn_kernel.cu — verify), which wraps the
flash-attention CUDA library. TPU-native design: a Pallas kernel tiled for
the MXU (128-lane) with online softmax, falling back to an XLA-fused
reference implementation (XLA fuses the softmax chain well; the Pallas path
wins on long sequences by avoiding the S×S materialization).

Layout convention is paddle's: (batch, seq, num_heads, head_dim).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np


def _xla_sdpa(q, k, v, mask=None, is_causal=False, dropout_p=0.0,
              scale=None):
    """Reference path: materializes scores; XLA fuses. bshd layout."""
    *_, sq, hq, d = q.shape
    sk = k.shape[1]
    hk = k.shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if hk != hq:  # GQA: repeat kv heads
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    # (b, h, sq, sk)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if is_causal:
        causal = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        scores = jnp.where(causal[None, None], scores, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, -jnp.inf)
        else:
            scores = scores + mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if dropout_p > 0.0:
        from ... import framework
        key = framework.split_key()
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p),
                          0.0).astype(probs.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _pallas_available() -> bool:
    try:
        import jax.experimental.pallas  # noqa: F401
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _pallas_flash(q, k, v, is_causal, scale):
    """Pallas online-softmax attention, grid over (batch*heads, q blocks)."""
    from jax.experimental import pallas as pl

    b, sq, h, d = q.shape
    sk = k.shape[1]
    blk_q = min(512, sq)
    blk_k = min(512, sk)
    if sq % blk_q or sk % blk_k or d % 128 or q.shape[2] != k.shape[2]:
        return None  # shapes don't tile; caller falls back

    qh = jnp.moveaxis(q, 2, 1).reshape(b * h, sq, d)
    kh = jnp.moveaxis(k, 2, 1).reshape(b * h, sk, d)
    vh = jnp.moveaxis(v, 2, 1).reshape(b * h, sk, d)

    def kernel(q_ref, k_ref, v_ref, o_ref):
        qi = pl.program_id(1)
        qv = q_ref[...].astype(jnp.float32) * scale
        m = jnp.full((blk_q,), -jnp.inf, jnp.float32)
        l = jnp.zeros((blk_q,), jnp.float32)
        acc = jnp.zeros((blk_q, d), jnp.float32)

        nkb = sk // blk_k

        def body(kb, carry):
            m, l, acc = carry
            kv = pl.load(k_ref, (pl.dslice(kb * blk_k, blk_k),
                                 pl.dslice(None))).astype(jnp.float32)
            vv = pl.load(v_ref, (pl.dslice(kb * blk_k, blk_k),
                                 pl.dslice(None))).astype(jnp.float32)
            s = qv @ kv.T  # (blk_q, blk_k)
            if is_causal:
                qpos = qi * blk_q + jax.lax.broadcasted_iota(
                    jnp.int32, (blk_q, blk_k), 0)
                kpos = kb * blk_k + jax.lax.broadcasted_iota(
                    jnp.int32, (blk_q, blk_k), 1)
                s = jnp.where(qpos >= kpos, s, -jnp.inf)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[:, None])
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1)
            acc_new = acc * alpha[:, None] + p @ vv
            return m_new, l_new, acc_new

        m, l, acc = jax.lax.fori_loop(0, nkb, body, (m, l, acc))
        o_ref[...] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(
            o_ref.dtype)

    from jax.experimental.pallas import BlockSpec

    out = pl.pallas_call(
        kernel,
        grid=(b * h, sq // blk_q),
        in_specs=[
            BlockSpec((None, blk_q, d), lambda i, j: (i, j, 0)),
            BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=BlockSpec((None, blk_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
    )(qh, kh, vh)
    return jnp.moveaxis(out.reshape(b, h, sq, d), 1, 2)


def sdpa(q, k, v, mask=None, is_causal=False, dropout_p=0.0, scale=None):
    """Scaled dot-product attention, bshd layout, fp32 accumulation."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if (mask is None and dropout_p == 0.0 and _pallas_available()):
        try:
            out = _pallas_flash(q, k, v, is_causal, scale)
            if out is not None:
                return out
        except Exception:
            pass
    return _xla_sdpa(q, k, v, mask, is_causal, dropout_p, scale)
