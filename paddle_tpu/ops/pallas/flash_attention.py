"""Flash attention for TPU.

Reference parity: phi FlashAttnKernel (reference:
paddle/phi/kernels/gpu/flash_attn_kernel.cu — verify), which wraps the
flash-attention CUDA library. TPU-native design: a Pallas kernel tiled for
the MXU (128-lane) with online softmax, falling back to an XLA-fused
reference implementation (XLA fuses the softmax chain well; the Pallas path
wins on long sequences by avoiding the S×S materialization).

Layout convention is paddle's: (batch, seq, num_heads, head_dim).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.flags import env_int, env_set, env_str


def _xla_sdpa(q, k, v, mask=None, is_causal=False, dropout_p=0.0,
              scale=None, window=None):
    """Reference path: materializes scores; XLA fuses. bshd layout.
    ``window``: sliding-window (Mistral-class) attention — each query
    attends to at most the last ``window`` keys."""
    *_, sq, hq, d = q.shape
    sk = k.shape[1]
    hk = k.shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if hk != hq:  # GQA: repeat kv heads
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    # (b, h, sq, sk)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if is_causal or window is not None:
        # sliding window implies causal banding even when the caller
        # supplies its own (e.g. padding) mask with is_causal=False —
        # otherwise training with masks and cached decode would silently
        # apply different attention patterns
        causal = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        if window is not None:
            # banded: q position p attends keys (p-window, p]
            band = jnp.triu(jnp.ones((sq, sk), bool),
                            k=sk - sq - int(window) + 1)
            causal = causal & band
        scores = jnp.where(causal[None, None], scores, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, -jnp.inf)
        else:
            scores = scores + mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if dropout_p > 0.0:
        from ... import framework
        key = framework.split_key()
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p),
                          0.0).astype(probs.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# tests set this to exercise the kernels in interpret mode on CPU
_FORCE_INTERPRET = False


def _pallas_available() -> bool:
    if _FORCE_INTERPRET:
        return True
    try:
        import jax.experimental.pallas  # noqa: F401
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _pick_block(s, pref=512):
    for blk in (pref, 256, 128, 64, 32, 16, 8):
        if s % blk == 0:
            return blk
    return None


def _band_mask(s, qi, kb, blk_q, blk_k, is_causal, window):
    """Apply causal and/or sliding-window banding to a (blk_q, blk_k)
    score tile at tile coords (qi, kb). ``window`` is a static int or
    None; window implies causal banding (sdpa convention)."""
    if not is_causal and window is None:
        return s
    qpos = qi * blk_q + jax.lax.broadcasted_iota(
        jnp.int32, (blk_q, blk_k), 0)
    kpos = kb * blk_k + jax.lax.broadcasted_iota(
        jnp.int32, (blk_q, blk_k), 1)
    keep = qpos >= kpos
    if window is not None:
        keep = keep & (qpos - kpos < int(window))
    return jnp.where(keep, s, -jnp.inf)


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                      is_causal, blk_q, blk_k, sk, d, window=None):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    qv = q_ref[...].astype(jnp.float32) * scale
    m = jnp.full((blk_q,), -jnp.inf, jnp.float32)
    l = jnp.zeros((blk_q,), jnp.float32)
    acc = jnp.zeros((blk_q, d), jnp.float32)
    nkb = sk // blk_k

    def body(kb, carry):
        m, l, acc = carry
        kv = k_ref[pl.ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        vv = v_ref[pl.ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        s = qv @ kv.T  # (blk_q, blk_k)
        s = _band_mask(s, qi, kb, blk_q, blk_k, is_causal, window)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # fully-masked-so-far rows (window band not reached yet) keep
        # m=-inf; exp(-inf - -inf) would NaN
        neg = m_new == -jnp.inf
        p = jnp.where(neg[:, None], 0.0, jnp.exp(s - m_new[:, None]))
        alpha = jnp.where(neg, 1.0, jnp.exp(m - m_new))
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + p @ vv
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, nkb, body, (m, l, acc))
    lsafe = jnp.maximum(l, 1e-30)
    o_ref[...] = (acc / lsafe[:, None]).astype(o_ref.dtype)
    lse_ref[...] = jnp.broadcast_to((m + jnp.log(lsafe))[:, None],
                                    lse_ref.shape)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, scale, is_causal, blk_q, blk_k, sk, d,
                         window=None):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    qv = q_ref[...].astype(jnp.float32)
    do = do_ref[...].astype(jnp.float32)
    lse = lse_ref[...][:, :1]        # (blk_q, 1) from the lane broadcast
    delta = delta_ref[...][:, :1]
    dq = jnp.zeros((blk_q, d), jnp.float32)
    nkb = sk // blk_k

    def body(kb, dq):
        kv = k_ref[pl.ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        vv = v_ref[pl.ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        s = (qv @ kv.T) * scale
        s = _band_mask(s, qi, kb, blk_q, blk_k, is_causal, window)
        p = jnp.exp(s - lse)
        dp = do @ vv.T
        ds = p * (dp - delta) * scale
        return dq + ds @ kv

    dq = jax.lax.fori_loop(0, nkb, body, dq)
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, scale, is_causal, blk_q,
                          blk_k, sq, d, window=None):
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    kv = k_ref[...].astype(jnp.float32)
    vv = v_ref[...].astype(jnp.float32)
    dk = jnp.zeros((blk_k, d), jnp.float32)
    dv = jnp.zeros((blk_k, d), jnp.float32)
    nqb = sq // blk_q

    def body(qb, carry):
        dk, dv = carry
        qv = q_ref[pl.ds(qb * blk_q, blk_q), :].astype(jnp.float32)
        do = do_ref[pl.ds(qb * blk_q, blk_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(qb * blk_q, blk_q), :1]
        delta = delta_ref[pl.ds(qb * blk_q, blk_q), :1]
        s = (qv @ kv.T) * scale        # (blk_q, blk_k)
        s = _band_mask(s, qb, ki, blk_q, blk_k, is_causal, window)
        p = jnp.exp(s - lse)
        dv = dv + p.T @ do
        dp = do @ vv.T
        ds = p * (dp - delta) * scale
        dk = dk + ds.T @ qv
        return dk, dv

    dk, dv = jax.lax.fori_loop(0, nqb, body, (dk, dv))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_prep(q, k, v):
    """(b,s,h,d) -> (b*h, s, d_pad) with head_dim zero-padded to 128
    lanes (zeros don't change q·k or p·v). k/v keep their OWN head count
    (b*kv_heads rows) — GQA never materializes repeated K/V; the kernels
    map q program i to kv row i // (h // kv_heads)."""
    b, sq, h, d = q.shape
    d_pad = max(128, (d + 127) // 128 * 128)

    def to3(x):
        hx = x.shape[2]
        x = jnp.moveaxis(x, 2, 1).reshape(b * hx, x.shape[1], d)
        if d_pad != d:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, d_pad - d)))
        return x
    return to3(q), to3(k), to3(v), d_pad


def _flash_call(kernel, grid, arrs, out_specs, out_shapes, blocks):
    from jax.experimental import pallas as pl
    return pl.pallas_call(
        kernel, grid=grid, in_specs=blocks, out_specs=out_specs,
        out_shape=out_shapes, interpret=_FORCE_INTERPRET)(*arrs)


def flash_attention_fused(q, k, v, is_causal=False, scale=None,
                          window=None):
    """Differentiable Pallas flash attention (bshd layout). Returns None
    when shapes don't tile (caller falls back to the XLA path).

    Memory: O(s) per program instance instead of the O(s^2) score matrix
    — both forward AND backward (two-pass dq / dkv kernels using the
    saved logsumexp; the reference's flash_attn_grad path equivalently:
    paddle/phi/kernels/gpu/flash_attn_grad_kernel.cu — verify).

    GQA: kv heads are NEVER repeated — the kernels index kv row
    i // rep via the BlockSpec index maps (VERDICT r2 weak #4).
    ``window``: sliding-window banding inside the kernels (implies
    causal, sdpa convention).

    This IS :func:`flash_block` with the logsumexp output discarded
    (its cotangent is then zero, so the shared backward kernels reduce
    to the plain flash gradient) — one custom-VJP implementation serves
    both the dense and the ring/context-parallel paths.
    """
    out = flash_block(q, k, v, is_causal=is_causal, scale=scale,
                      window=window)
    if out is None:
        return None
    return out[0]


def flash_block(q, k, v, is_causal=False, scale=None, window=None):
    """One (q-shard × kv-shard) flash attention block: returns
    ``(o, lse)`` where ``o`` (b, sq, h, d) is the block-normalized
    attention output and ``lse`` (b, h, sq) its logsumexp — the pair the
    ring merge combines across hops (the reference threads the CUDA
    kernel's softmax_lse identically: PaddleNLP ring_flash_attention.py
    — verify); plain flash attention is this with the lse discarded
    (see flash_attention_fused). Differentiable with cotangents for
    BOTH outputs: d(lse)/d(scores) is the softmax, so the lse cotangent
    folds into the backward kernels' delta term
    (ds = p·(dp − (delta − dlse))). GQA-aware (no K/V repeat);
    ``window`` bands the scores inside the kernels (implies causal).
    Returns None when shapes don't tile."""
    b, sq, h, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    blk_q = _pick_block(sq)
    blk_k = _pick_block(sk)
    if blk_q is None or blk_k is None or blk_q < 8 or blk_k < 8 \
            or h % hk != 0:
        return None
    rep = h // hk
    if window is not None:
        is_causal = True            # window implies causal banding

    import functools as ft
    from jax.experimental.pallas import BlockSpec

    def kv_row(i, j):
        return (i // rep, 0, 0)

    def kv_blk_row(i, j):
        return (i // rep, j, 0)

    @jax.custom_vjp
    def fb(q, k, v):
        return _fb_fwd(q, k, v)[0]

    def _fb_fwd(q, k, v):
        qh, kh, vh, d_pad = _flash_prep(q, k, v)
        bh = qh.shape[0]
        out, lse = _flash_call(
            ft.partial(_flash_fwd_kernel, scale=scale,
                       is_causal=is_causal, blk_q=blk_q, blk_k=blk_k,
                       sk=sk, d=d_pad, window=window),
            (bh, sq // blk_q),
            (qh, kh, vh),
            [BlockSpec((None, blk_q, d_pad), lambda i, j: (i, j, 0)),
             BlockSpec((None, blk_q, 128), lambda i, j: (i, j, 0))],
            [jax.ShapeDtypeStruct((bh, sq, d_pad), q.dtype),
             jax.ShapeDtypeStruct((bh, sq, 128), jnp.float32)],
            [BlockSpec((None, blk_q, d_pad), lambda i, j: (i, j, 0)),
             BlockSpec((None, sk, d_pad), kv_row),
             BlockSpec((None, sk, d_pad), kv_row)])
        o4 = jnp.moveaxis(out[..., :d].reshape(b, h, sq, d), 1, 2)
        lse3 = lse[:, :, 0].reshape(b, h, sq)
        return (o4, lse3), (q, k, v, o4, lse)

    def _fb_bwd(saved, cts):
        ct, dlse3 = cts
        q, k, v, o, lse = saved
        qh, kh, vh, d_pad = _flash_prep(q, k, v)
        doh = _flash_prep(ct, ct, ct)[0]
        bh = qh.shape[0]
        # delta' = rowsum(do · o) − dlse: the lse cotangent enters the
        # shared backward kernels through the delta slot
        delta = jnp.sum(
            (jnp.moveaxis(ct, 2, 1).reshape(bh, sq, d)
             * jnp.moveaxis(o, 2, 1).reshape(bh, sq, d)).astype(
                 jnp.float32), axis=-1)
        delta = delta - dlse3.reshape(bh, sq).astype(jnp.float32)
        delta = jnp.broadcast_to(delta[..., None], (bh, sq, 128))
        dq = _flash_call(
            ft.partial(_flash_bwd_dq_kernel, scale=scale,
                       is_causal=is_causal, blk_q=blk_q, blk_k=blk_k,
                       sk=sk, d=d_pad, window=window),
            (bh, sq // blk_q),
            (qh, kh, vh, doh, lse, delta),
            BlockSpec((None, blk_q, d_pad), lambda i, j: (i, j, 0)),
            jax.ShapeDtypeStruct((bh, sq, d_pad), jnp.float32),
            [BlockSpec((None, blk_q, d_pad), lambda i, j: (i, j, 0)),
             BlockSpec((None, sk, d_pad), kv_row),
             BlockSpec((None, sk, d_pad), kv_row),
             BlockSpec((None, blk_q, d_pad), lambda i, j: (i, j, 0)),
             BlockSpec((None, blk_q, 128), lambda i, j: (i, j, 0)),
             BlockSpec((None, blk_q, 128), lambda i, j: (i, j, 0))])
        dk, dv = _flash_call(
            ft.partial(_flash_bwd_dkv_kernel, scale=scale,
                       is_causal=is_causal, blk_q=blk_q, blk_k=blk_k,
                       sq=sq, d=d_pad, window=window),
            (bh, sk // blk_k),
            (qh, kh, vh, doh, lse, delta),
            [BlockSpec((None, blk_k, d_pad), lambda i, j: (i, j, 0)),
             BlockSpec((None, blk_k, d_pad), lambda i, j: (i, j, 0))],
            [jax.ShapeDtypeStruct((bh, sk, d_pad), jnp.float32),
             jax.ShapeDtypeStruct((bh, sk, d_pad), jnp.float32)],
            [BlockSpec((None, sq, d_pad), lambda i, j: (i, 0, 0)),
             BlockSpec((None, blk_k, d_pad), kv_blk_row),
             BlockSpec((None, blk_k, d_pad), kv_blk_row),
             BlockSpec((None, sq, d_pad), lambda i, j: (i, 0, 0)),
             BlockSpec((None, sq, 128), lambda i, j: (i, 0, 0)),
             BlockSpec((None, sq, 128), lambda i, j: (i, 0, 0))])

        def back_q(x):
            x = x[..., :d].reshape(b, h, sq, d)
            return jnp.moveaxis(x, 1, 2).astype(q.dtype)

        def back_kv(x):
            x = x[..., :d].reshape(b, h, sk, d)
            if rep > 1:
                x = x.reshape(b, hk, rep, sk, d).sum(axis=2)
            return jnp.moveaxis(x, 1, 2).astype(q.dtype)

        return back_q(dq), back_kv(dk), back_kv(dv)

    fb.defvjp(_fb_fwd, _fb_bwd)
    return fb(q, k, v)


# the effective block choice of the most recent tiled-kernel dispatch:
# {"kernel", "source": "env"|"tuned"|"default", "block_q", "block_kv"}
# — recorded so bench A/Bs can ATTRIBUTE a number to the block config
# that produced it instead of guessing from the environment
LAST_BLOCK_CHOICE = {"kernel": "none", "source": "default",
                     "block_q": None, "block_kv": None}


def last_block_choice() -> dict:
    return dict(LAST_BLOCK_CHOICE)


def _block_pref(env_name: str, kernel: str, seq: int, dim: int,
                default: int = 512):
    """Resolve a kernel's preferred block size: explicit env override
    (routed through utils/flags.env_int, 0 = kernel defaults) beats a
    valid autotune-table entry beats the PROFILE_r03 default (512).
    Returns (pref, source)."""
    if env_set(env_name):     # presence check: NAME=0 still means "env"
        return env_int(env_name, default), "env"
    from .autotune import lookup
    cfg = lookup("flash_attention", {"seq": seq, "dim": dim})
    if cfg and int(cfg.get("block_kv", 0)) > 0:
        return int(cfg["block_kv"]), "tuned"
    return default, "default"


def _note_blocks(kernel, source, bq, bk):
    LAST_BLOCK_CHOICE.update(kernel=kernel, source=source, block_q=bq,
                             block_kv=bk)


def _jax_flash_blocks(jfa, sq, sk, dim=128):
    """Block sizes for jax's TPU flash kernel. The kernel's built-in
    default is 128 everywhere; PROFILE_r03 (v5e, b32 h16 s1024 d64)
    measured the three 128-block kernels at 53% of device self-time for
    ~14% of step FLOPs. Bigger tiles amortize the HBM traffic per score
    tile — FLASH_BLOCKS_r03.json records the on-chip sweep; 512 wins,
    unless the autotune table holds a fresher per-device winner.
    Env overrides: PT_JAX_FLASH_BLOCK (kv block), PT_JAX_FLASH_BLOCK_Q.
    Returns None (= kernel default) when the sequence doesn't tile."""
    pref, source = _block_pref("PT_JAX_FLASH_BLOCK", "jax_flash", sk,
                               dim)
    pref_q = env_int("PT_JAX_FLASH_BLOCK_Q", pref)
    bq = _pick_block(sq, min(pref_q, sq))
    bk = _pick_block(sk, min(pref, sk))
    if bq is None or bk is None or (bq <= 128 and bk <= 128):
        _note_blocks("jax_flash", source, None, None)
        return None
    _note_blocks("jax_flash", source, bq, bk)
    return jfa.BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
        block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk,
        block_q_dq=bq)


def _jax_tpu_flash(q, k, v, is_causal, scale):
    """jax's tuned Pallas TPU flash kernel (differentiable), bhsd layout.
    Returns None if shapes are unsupported. Equal q/kv head counts only —
    GQA takes the splash path (no K/V materialization)."""
    if _FORCE_INTERPRET:
        return None     # interpret-mode tests target OUR kernels
    try:
        from jax.experimental.pallas.ops.tpu import flash_attention as jfa
    except ImportError:
        return None
    if k.shape[2] != q.shape[2]:
        return None
    blocks = _jax_flash_blocks(jfa, q.shape[1], k.shape[1], q.shape[3])
    try:
        out = jfa.flash_attention(
            jnp.moveaxis(q, 2, 1), jnp.moveaxis(k, 2, 1),
            jnp.moveaxis(v, 2, 1), causal=is_causal, sm_scale=scale,
            block_sizes=blocks)
    except (ValueError, NotImplementedError):
        if blocks is None:
            return None
        try:  # tuned blocks rejected for this shape: kernel defaults
            out = jfa.flash_attention(
                jnp.moveaxis(q, 2, 1), jnp.moveaxis(k, 2, 1),
                jnp.moveaxis(v, 2, 1), causal=is_causal, sm_scale=scale)
        except (ValueError, NotImplementedError):
            return None
    return jnp.moveaxis(out, 1, 2)


def _splash_attention(q, k, v, is_causal, scale, window=None):
    """jax's splash-attention TPU kernel: native GQA (q heads grouped
    over kv heads — K/V never repeated) and native sliding-window via
    LocalMask (block-sparse: fully-masked tiles are SKIPPED, unlike the
    banded-masking fallbacks). bshd layout. Returns None when shapes
    don't fit the kernel.

    Reference parity: the flash-attn CUDA wrapper's GQA/window args
    (paddle/phi/kernels/gpu/flash_attn_kernel.cu — verify)."""
    try:
        from jax.experimental.pallas.ops.tpu.splash_attention import (
            splash_attention_kernel as sak,
            splash_attention_mask as sam)
    except ImportError:
        return None
    b, sq, h, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    if h % hk != 0:
        return None
    g = h // hk
    if window is not None:
        m = sam.LocalMask((sq, sk), window_size=(int(window) - 1, 0),
                          offset=0)
    elif is_causal:
        m = sam.CausalMask((sq, sk))
    else:
        m = sam.FullMask((sq, sk))
    # splash's built-in default is 128-tiles everywhere — the same
    # tiling PROFILE_r03 measured at 53% of step time on the jax flash
    # kernel; hand it 512-class tiles when the sequence tiles
    # (PT_SPLASH_BLOCK overrides via utils/flags.env_int, 0 = kernel
    # defaults; a valid autotune-table entry beats the 512 default)
    pref, source = _block_pref("PT_SPLASH_BLOCK", "splash", sk, d)
    blocks = None
    bq = _pick_block(sq, min(pref, sq)) if pref else None
    bk = _pick_block(sk, min(pref, sk)) if pref else None
    _note_blocks("splash", source, bq if bq and bk else None,
                 bk if bq and bk else None)
    if bq and bk and (bq > 128 or bk > 128):
        blocks = sak.BlockSizes(
            block_q=bq, block_kv=bk, block_kv_compute=bk,
            block_q_dkv=bq, block_kv_dkv=bk, block_kv_dkv_compute=bk,
            block_q_dq=bq, block_kv_dq=bk)
    try:
        kern = sak.make_splash_mqa_single_device(
            sam.MultiHeadMask([m] * g), block_sizes=blocks,
            interpret=_FORCE_INTERPRET)
        qs = (q * jnp.asarray(scale, q.dtype))
        # (b, s, h, d) -> (b, kvh, g, s, d); kv -> (b, kvh, s, d)
        qq = jnp.moveaxis(qs, 2, 1).reshape(b, hk, g, sq, d)
        kk = jnp.moveaxis(k, 2, 1)
        vv = jnp.moveaxis(v, 2, 1)
        out = jax.vmap(jax.vmap(kern))(qq, kk, vv)  # (b, kvh, g, sq, d)
    except (ValueError, NotImplementedError):
        return None
    return jnp.moveaxis(out.reshape(b, h, sq, d), 1, 2)


# route taken by the most recent sdpa() trace: "jax_flash" | "fused_flash"
# | "xla".  Inspectable by bench.py / on-hardware tests so a broken Pallas
# kernel can never silently masquerade as the fast path (VERDICT r1 weak #2).
LAST_DISPATCH = "none"
_FALLBACK_WARNED = False


def sdpa_last_dispatch() -> str:
    return LAST_DISPATCH


def sdpa(q, k, v, mask=None, is_causal=False, dropout_p=0.0, scale=None,
         window=None):
    """Scaled dot-product attention, bshd layout, fp32 accumulation.
    TPU dispatch order: splash kernel (GQA and/or sliding-window —
    block-sparse, no K/V repeat) -> jax's tuned flash kernel (equal
    heads) -> our fused flash kernel (GQA + window aware) -> XLA-fused
    reference (O(s^2) scores)."""
    global LAST_DISPATCH, _FALLBACK_WARNED
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if (mask is None and dropout_p == 0.0 and _pallas_available()):
        # trace-time failures in any Pallas path fall back to XLA
        # (compile-time Mosaic errors surface later and are covered by
        # the on-hardware kernel tests)
        gqa = k.shape[2] != q.shape[2]
        # PT_SDPA_PREFER overrides the equal-heads route for on-chip
        # A/B ("splash" | "jax_flash" | "fused"); GQA/window always
        # prefer splash (the only kernel that avoids K/V repeat)
        prefer = env_str("PT_SDPA_PREFER")
        try:
            if gqa or window is not None or prefer == "splash":
                out = _splash_attention(q, k, v, is_causal, scale, window)
                if out is not None:
                    LAST_DISPATCH = "splash"
                    return out
            elif prefer != "fused":
                out = _jax_tpu_flash(q, k, v, is_causal, scale)
                if out is not None:
                    LAST_DISPATCH = "jax_flash"
                    return out
            out = flash_attention_fused(q, k, v, is_causal, scale,
                                        window=window)
            if out is not None:
                LAST_DISPATCH = "fused_flash"
                return out
        except Exception as e:
            if not _FALLBACK_WARNED:
                _FALLBACK_WARNED = True
                import warnings
                warnings.warn(
                    f"Pallas flash attention unavailable, falling back to "
                    f"O(s^2) XLA attention: {type(e).__name__}: {e}",
                    RuntimeWarning)
    LAST_DISPATCH = "xla"
    return _xla_sdpa(q, k, v, mask, is_causal, dropout_p, scale,
                     window=window)
