"""Flash attention for TPU.

Reference parity: phi FlashAttnKernel (reference:
paddle/phi/kernels/gpu/flash_attn_kernel.cu — verify), which wraps the
flash-attention CUDA library. TPU-native design: a Pallas kernel tiled for
the MXU (128-lane) with online softmax, falling back to an XLA-fused
reference implementation (XLA fuses the softmax chain well; the Pallas path
wins on long sequences by avoiding the S×S materialization).

Layout convention is paddle's: (batch, seq, num_heads, head_dim).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np


def _xla_sdpa(q, k, v, mask=None, is_causal=False, dropout_p=0.0,
              scale=None, window=None):
    """Reference path: materializes scores; XLA fuses. bshd layout.
    ``window``: sliding-window (Mistral-class) attention — each query
    attends to at most the last ``window`` keys."""
    *_, sq, hq, d = q.shape
    sk = k.shape[1]
    hk = k.shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if hk != hq:  # GQA: repeat kv heads
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    # (b, h, sq, sk)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if is_causal or window is not None:
        # sliding window implies causal banding even when the caller
        # supplies its own (e.g. padding) mask with is_causal=False —
        # otherwise training with masks and cached decode would silently
        # apply different attention patterns
        causal = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        if window is not None:
            # banded: q position p attends keys (p-window, p]
            band = jnp.triu(jnp.ones((sq, sk), bool),
                            k=sk - sq - int(window) + 1)
            causal = causal & band
        scores = jnp.where(causal[None, None], scores, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, -jnp.inf)
        else:
            scores = scores + mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if dropout_p > 0.0:
        from ... import framework
        key = framework.split_key()
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p),
                          0.0).astype(probs.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# tests set this to exercise the kernels in interpret mode on CPU
_FORCE_INTERPRET = False


def _pallas_available() -> bool:
    if _FORCE_INTERPRET:
        return True
    try:
        import jax.experimental.pallas  # noqa: F401
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _pick_block(s, pref=512):
    for blk in (pref, 256, 128, 64, 32, 16, 8):
        if s % blk == 0:
            return blk
    return None


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                      is_causal, blk_q, blk_k, sk, d):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    qv = q_ref[...].astype(jnp.float32) * scale
    m = jnp.full((blk_q,), -jnp.inf, jnp.float32)
    l = jnp.zeros((blk_q,), jnp.float32)
    acc = jnp.zeros((blk_q, d), jnp.float32)
    nkb = sk // blk_k

    def body(kb, carry):
        m, l, acc = carry
        kv = k_ref[pl.ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        vv = v_ref[pl.ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        s = qv @ kv.T  # (blk_q, blk_k)
        if is_causal:
            qpos = qi * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0)
            kpos = kb * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(qpos >= kpos, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + p @ vv
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, nkb, body, (m, l, acc))
    lsafe = jnp.maximum(l, 1e-30)
    o_ref[...] = (acc / lsafe[:, None]).astype(o_ref.dtype)
    lse_ref[...] = jnp.broadcast_to((m + jnp.log(lsafe))[:, None],
                                    lse_ref.shape)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, scale, is_causal, blk_q, blk_k, sk, d):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    qv = q_ref[...].astype(jnp.float32)
    do = do_ref[...].astype(jnp.float32)
    lse = lse_ref[...][:, :1]        # (blk_q, 1) from the lane broadcast
    delta = delta_ref[...][:, :1]
    dq = jnp.zeros((blk_q, d), jnp.float32)
    nkb = sk // blk_k

    def body(kb, dq):
        kv = k_ref[pl.ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        vv = v_ref[pl.ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        s = (qv @ kv.T) * scale
        if is_causal:
            qpos = qi * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0)
            kpos = kb * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(qpos >= kpos, s, -jnp.inf)
        p = jnp.exp(s - lse)
        dp = do @ vv.T
        ds = p * (dp - delta) * scale
        return dq + ds @ kv

    dq = jax.lax.fori_loop(0, nkb, body, dq)
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, scale, is_causal, blk_q,
                          blk_k, sq, d):
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    kv = k_ref[...].astype(jnp.float32)
    vv = v_ref[...].astype(jnp.float32)
    dk = jnp.zeros((blk_k, d), jnp.float32)
    dv = jnp.zeros((blk_k, d), jnp.float32)
    nqb = sq // blk_q

    def body(qb, carry):
        dk, dv = carry
        qv = q_ref[pl.ds(qb * blk_q, blk_q), :].astype(jnp.float32)
        do = do_ref[pl.ds(qb * blk_q, blk_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(qb * blk_q, blk_q), :1]
        delta = delta_ref[pl.ds(qb * blk_q, blk_q), :1]
        s = (qv @ kv.T) * scale        # (blk_q, blk_k)
        if is_causal:
            qpos = qb * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0)
            kpos = ki * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(qpos >= kpos, s, -jnp.inf)
        p = jnp.exp(s - lse)
        dv = dv + p.T @ do
        dp = do @ vv.T
        ds = p * (dp - delta) * scale
        dk = dk + ds.T @ qv
        return dk, dv

    dk, dv = jax.lax.fori_loop(0, nqb, body, (dk, dv))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_prep(q, k, v):
    """(b,s,h,d) -> (b*h, s, d_pad) with head_dim zero-padded to 128
    lanes (zeros don't change q·k or p·v)."""
    b, sq, h, d = q.shape
    d_pad = max(128, (d + 127) // 128 * 128)

    def to3(x):
        x = jnp.moveaxis(x, 2, 1).reshape(b * h, x.shape[1], d)
        if d_pad != d:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, d_pad - d)))
        return x
    return to3(q), to3(k), to3(v), d_pad


def _flash_call(kernel, grid, arrs, out_specs, out_shapes, blocks):
    from jax.experimental import pallas as pl
    return pl.pallas_call(
        kernel, grid=grid, in_specs=blocks, out_specs=out_specs,
        out_shape=out_shapes, interpret=_FORCE_INTERPRET)(*arrs)


def flash_attention_fused(q, k, v, is_causal=False, scale=None):
    """Differentiable Pallas flash attention (bshd layout). Returns None
    when shapes don't tile (caller falls back to the XLA path).

    Memory: O(s) per program instance instead of the O(s^2) score matrix
    — both forward AND backward (two-pass dq / dkv kernels using the
    saved logsumexp; the reference's flash_attn_grad path equivalently:
    paddle/phi/kernels/gpu/flash_attn_grad_kernel.cu — verify)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    blk_q = _pick_block(sq)
    blk_k = _pick_block(sk)
    if blk_q is None or blk_k is None or blk_q < 8 or blk_k < 8:
        return None
    if k.shape[2] != h:
        rep = h // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    import functools as ft
    from jax.experimental.pallas import BlockSpec

    @jax.custom_vjp
    def fa(q, k, v):
        return _fa_fwd(q, k, v)[0]

    def _fa_fwd(q, k, v):
        qh, kh, vh, d_pad = _flash_prep(q, k, v)
        bh = qh.shape[0]
        out, lse = _flash_call(
            ft.partial(_flash_fwd_kernel, scale=scale, is_causal=is_causal,
                       blk_q=blk_q, blk_k=blk_k, sk=sk, d=d_pad),
            (bh, sq // blk_q),
            (qh, kh, vh),
            [BlockSpec((None, blk_q, d_pad), lambda i, j: (i, j, 0)),
             BlockSpec((None, blk_q, 128), lambda i, j: (i, j, 0))],
            [jax.ShapeDtypeStruct((bh, sq, d_pad), q.dtype),
             jax.ShapeDtypeStruct((bh, sq, 128), jnp.float32)],
            [BlockSpec((None, blk_q, d_pad), lambda i, j: (i, j, 0)),
             BlockSpec((None, sk, d_pad), lambda i, j: (i, 0, 0)),
             BlockSpec((None, sk, d_pad), lambda i, j: (i, 0, 0))])
        o4 = jnp.moveaxis(out[..., :d].reshape(b, h, sq, d), 1, 2)
        return o4, (q, k, v, o4, lse)

    def _fa_bwd(saved, ct):
        q, k, v, o, lse = saved
        qh, kh, vh, d_pad = _flash_prep(q, k, v)
        doh = _flash_prep(ct, ct, ct)[0]
        bh = qh.shape[0]
        # delta = rowsum(do * o) per query position
        delta = jnp.sum(
            (jnp.moveaxis(ct, 2, 1).reshape(bh, sq, d)
             * jnp.moveaxis(o, 2, 1).reshape(bh, sq, d)).astype(
                 jnp.float32), axis=-1)
        delta = jnp.broadcast_to(delta[..., None], (bh, sq, 128))
        dq = _flash_call(
            ft.partial(_flash_bwd_dq_kernel, scale=scale,
                       is_causal=is_causal, blk_q=blk_q, blk_k=blk_k,
                       sk=sk, d=d_pad),
            (bh, sq // blk_q),
            (qh, kh, vh, doh, lse, delta),
            BlockSpec((None, blk_q, d_pad), lambda i, j: (i, j, 0)),
            jax.ShapeDtypeStruct((bh, sq, d_pad), jnp.float32),
            [BlockSpec((None, blk_q, d_pad), lambda i, j: (i, j, 0)),
             BlockSpec((None, sk, d_pad), lambda i, j: (i, 0, 0)),
             BlockSpec((None, sk, d_pad), lambda i, j: (i, 0, 0)),
             BlockSpec((None, blk_q, d_pad), lambda i, j: (i, j, 0)),
             BlockSpec((None, blk_q, 128), lambda i, j: (i, j, 0)),
             BlockSpec((None, blk_q, 128), lambda i, j: (i, j, 0))])
        dk, dv = _flash_call(
            ft.partial(_flash_bwd_dkv_kernel, scale=scale,
                       is_causal=is_causal, blk_q=blk_q, blk_k=blk_k,
                       sq=sq, d=d_pad),
            (bh, sk // blk_k),
            (qh, kh, vh, doh, lse, delta),
            [BlockSpec((None, blk_k, d_pad), lambda i, j: (i, j, 0)),
             BlockSpec((None, blk_k, d_pad), lambda i, j: (i, j, 0))],
            [jax.ShapeDtypeStruct((bh, sk, d_pad), jnp.float32),
             jax.ShapeDtypeStruct((bh, sk, d_pad), jnp.float32)],
            [BlockSpec((None, sq, d_pad), lambda i, j: (i, 0, 0)),
             BlockSpec((None, blk_k, d_pad), lambda i, j: (i, j, 0)),
             BlockSpec((None, blk_k, d_pad), lambda i, j: (i, j, 0)),
             BlockSpec((None, sq, d_pad), lambda i, j: (i, 0, 0)),
             BlockSpec((None, sq, 128), lambda i, j: (i, 0, 0)),
             BlockSpec((None, sq, 128), lambda i, j: (i, 0, 0))])

        def back4(x, s_len):
            x = x[..., :d].reshape(b, h, s_len, d)
            return jnp.moveaxis(x, 1, 2).astype(q.dtype)

        return back4(dq, sq), back4(dk, sk), back4(dv, sk)

    fa.defvjp(_fa_fwd, _fa_bwd)
    return fa(q, k, v)


def _jax_tpu_flash(q, k, v, is_causal, scale):
    """jax's tuned Pallas TPU flash kernel (differentiable), bhsd layout.
    Returns None if shapes are unsupported."""
    if _FORCE_INTERPRET:
        return None     # interpret-mode tests target OUR kernels
    try:
        from jax.experimental.pallas.ops.tpu import flash_attention as jfa
    except ImportError:
        return None
    b, sq, h, d = q.shape
    if k.shape[2] != h:
        rep = h // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    try:
        out = jfa.flash_attention(
            jnp.moveaxis(q, 2, 1), jnp.moveaxis(k, 2, 1),
            jnp.moveaxis(v, 2, 1), causal=is_causal, sm_scale=scale)
    except (ValueError, NotImplementedError):
        return None
    return jnp.moveaxis(out, 1, 2)


# route taken by the most recent sdpa() trace: "jax_flash" | "fused_flash"
# | "xla".  Inspectable by bench.py / on-hardware tests so a broken Pallas
# kernel can never silently masquerade as the fast path (VERDICT r1 weak #2).
LAST_DISPATCH = "none"
_FALLBACK_WARNED = False


def sdpa_last_dispatch() -> str:
    return LAST_DISPATCH


def sdpa(q, k, v, mask=None, is_causal=False, dropout_p=0.0, scale=None,
         window=None):
    """Scaled dot-product attention, bshd layout, fp32 accumulation.
    TPU dispatch order: jax's tuned flash kernel -> our fused flash
    kernel -> XLA-fused reference (O(s^2) scores). ``window`` (sliding
    window) currently runs the masked XLA path."""
    global LAST_DISPATCH, _FALLBACK_WARNED
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if window is not None:
        LAST_DISPATCH = "xla"
        return _xla_sdpa(q, k, v, mask, is_causal, dropout_p, scale,
                         window=window)
    if (mask is None and dropout_p == 0.0 and _pallas_available()):
        # trace-time failures in either Pallas path fall back to XLA
        # (compile-time Mosaic errors surface later and are covered by
        # the on-hardware kernel tests)
        try:
            out = _jax_tpu_flash(q, k, v, is_causal, scale)
            if out is not None:
                LAST_DISPATCH = "jax_flash"
                return out
            out = flash_attention_fused(q, k, v, is_causal, scale)
            if out is not None:
                LAST_DISPATCH = "fused_flash"
                return out
        except Exception as e:
            if not _FALLBACK_WARNED:
                _FALLBACK_WARNED = True
                import warnings
                warnings.warn(
                    f"Pallas flash attention unavailable, falling back to "
                    f"O(s^2) XLA attention: {type(e).__name__}: {e}",
                    RuntimeWarning)
    LAST_DISPATCH = "xla"
    return _xla_sdpa(q, k, v, mask, is_causal, dropout_p, scale)
