"""Op metadata registry — the single source of truth about ops.

Reference parity: paddle/phi/ops/yaml/{ops,backward}.yaml + the
generator pipeline (paddle/fluid/operators/generator/ — verify): one
table drives API emission, AMP lists, inplace maps, and dtype rules.

TPU-native design: no codegen is needed (jax.vjp derives backwards, XLA
owns kernels), so the registry's job is METADATA: per-op AMP category
(consulted by paddle_tpu.amp), differentiability, inplace variants, and
integer support. Ops are auto-discovered from the ops modules and
curated tags are overlaid; unknown ops default to amp-neutral, which is
always numerically safe."""
from __future__ import annotations

import dataclasses
import inspect
from typing import Dict, Optional

__all__ = ["OpMeta", "register_op", "get_op_meta", "ops_by_amp",
           "all_ops", "amp_white_list", "amp_black_list"]


@dataclasses.dataclass(frozen=True)
class OpMeta:
    name: str
    module: str = ""
    # AMP category (reference: python/paddle/amp/amp_lists.py — verify):
    # "white" = compute-bound, run in bf16/fp16 (matmul/conv class);
    # "black" = numerically sensitive, keep fp32 (softmax/norm/reduce);
    # "neutral" = follow inputs
    amp: str = "neutral"
    differentiable: bool = True
    inplace_variant: Optional[str] = None   # e.g. "add" -> "add_"
    integer_ok: bool = True


_REGISTRY: Dict[str, OpMeta] = {}

# curated AMP tags (the reference's amp_lists, expressed as metadata)
_AMP_WHITE = {"matmul", "mm", "bmm", "einsum", "linear", "conv1d",
              "conv2d", "conv3d", "conv2d_transpose", "addmm", "dot",
              "outer", "matmul_with_flatten"}
_AMP_BLACK = {"softmax", "log_softmax", "cross_entropy", "exp", "expm1",
              "log", "log2", "log10", "log1p", "mean", "sum", "prod",
              "norm", "layer_norm", "batch_norm", "instance_norm",
              "group_norm", "rms_norm", "softplus", "cumsum", "cumprod",
              "logsumexp", "sigmoid", "log_sigmoid", "erf", "erfinv",
              "var", "std", "nll_loss", "kl_div", "smooth_l1_loss",
              "binary_cross_entropy", "binary_cross_entropy_with_logits",
              "square_error_cost", "cosine_similarity", "pow", "rsqrt",
              "acos", "asin", "atan", "cosh", "sinh", "tan", "renorm",
              "dist", "pdist"}
_NON_DIFF = {"argmax", "argmin", "argsort", "equal", "not_equal",
             "greater_than", "greater_equal", "less_than", "less_equal",
             "logical_and", "logical_or", "logical_not", "logical_xor",
             "isnan", "isinf", "isfinite", "sign", "floor_divide",
             "mod", "bitwise_and", "bitwise_or", "bitwise_xor",
             "bitwise_not", "shape", "rank", "numel", "nonzero",
             "unique", "bincount", "searchsorted", "count_nonzero"}
_FLOAT_ONLY = {"softmax", "log_softmax", "exp", "log", "sqrt", "rsqrt",
               "sigmoid", "tanh", "erf", "sin", "cos", "layer_norm",
               "batch_norm", "rms_norm", "mean", "var", "std"}


_BOOTSTRAPPED = [False]


def register_op(name: str, **kw) -> OpMeta:
    _ensure()   # user registrations must not suppress auto-discovery
    meta = OpMeta(name=name, **kw)
    _REGISTRY[name] = meta
    return meta


def _categorize(name: str, module: str) -> OpMeta:
    return OpMeta(
        name=name, module=module,
        amp=("white" if name in _AMP_WHITE
             else "black" if name in _AMP_BLACK else "neutral"),
        differentiable=name not in _NON_DIFF,
        inplace_variant=name + "_" if name + "_" in _REGISTRY else None,
        integer_ok=name not in _FLOAT_ONLY)


def _bootstrap():
    from . import creation, manipulation, math as math_ops
    from ..nn import functional as F
    for mod in (math_ops, manipulation, creation, F):
        public = getattr(mod, "__all__", None) or [
            n for n in vars(mod) if not n.startswith("_")]
        for n in public:
            fn = getattr(mod, n, None)
            if not callable(fn) or inspect.isclass(fn):
                continue
            if n not in _REGISTRY:
                _REGISTRY[n] = _categorize(n, mod.__name__)
    # second pass: now that every name exists, link inplace variants
    for n, meta in list(_REGISTRY.items()):
        if not n.endswith("_") and n + "_" in _REGISTRY \
                and meta.inplace_variant is None:
            _REGISTRY[n] = dataclasses.replace(meta,
                                               inplace_variant=n + "_")


def _ensure():
    if not _BOOTSTRAPPED[0]:
        _BOOTSTRAPPED[0] = True
        _bootstrap()


def get_op_meta(name: str) -> Optional[OpMeta]:
    _ensure()
    return _REGISTRY.get(name)


def all_ops() -> Dict[str, OpMeta]:
    _ensure()
    return dict(_REGISTRY)


def ops_by_amp(category: str):
    _ensure()
    return {n for n, m in _REGISTRY.items() if m.amp == category}


def amp_white_list():
    """Names AMP runs in the low dtype — registry-derived, plus curated
    names whose ops live outside the scanned modules."""
    _ensure()
    return ops_by_amp("white") | _AMP_WHITE


def amp_black_list():
    _ensure()
    return ops_by_amp("black") | _AMP_BLACK
