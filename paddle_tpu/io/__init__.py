"""paddle_tpu.io — datasets and DataLoader.

Reference parity: python/paddle/io/ — verify (Dataset/IterableDataset,
samplers, DistributedBatchSampler per-rank sharding, multiprocess DataLoader
with shared-memory queues). TPU-native design: the loader yields host numpy
batches (collated) that feed jitted steps; prefetching is a background
thread pool (XLA dispatch is already async; device transfer overlaps), and
``num_workers>0, use_shared_memory=True`` uses forked worker PROCESSES
pushing batches through the C++ shared-memory ring of paddle_tpu.core
(one memcpy each way — the reference's shm _SharedQueue path)."""
from __future__ import annotations

import bisect
import itertools
import math
import queue
import threading
from typing import Iterable, Optional

import numpy as np

from ..tensor import Tensor, to_tensor

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ChainDataset",
           "ComposeDataset", "Subset", "random_split", "Sampler",
           "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
           "BatchSampler", "DistributedBatchSampler", "DataLoader",
           "default_collate_fn", "get_worker_info"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        n = len(dataset)
        lengths = [int(math.floor(n * f)) for f in lengths]
        lengths[-1] += len(dataset) - sum(lengths)
    total = sum(lengths)
    perm = np.random.permutation(total).tolist()
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l]))
        off += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Per-rank sharded batches (reference:
    python/paddle/io/dataloader/batch_sampler.py DistributedBatchSampler
    — verify). On TPU, rank defaults to the jax process index so multi-host
    input pipelines shard automatically."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            import jax
            num_replicas = num_replicas or jax.process_count()
            rank = rank if rank is not None else jax.process_index()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        # pad to make evenly divisible
        indices += indices[: self.total_size - n]
        # contiguous per-rank shard (paddle convention)
        indices = indices[self.local_rank * self.num_samples:
                          (self.local_rank + 1) * self.num_samples]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


class _WorkerInfo:
    def __init__(self, id=0, num_workers=1, dataset=None):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = None


def get_worker_info():
    return _worker_info


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        vals = np.stack([np.asarray(s._value) for s in batch])
        return to_tensor(vals)
    if isinstance(sample, np.ndarray):
        return to_tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return to_tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = zip(*batch)
        return [default_collate_fn(list(s)) for s in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, str):
        return list(batch)
    return to_tensor(np.asarray(batch))


class DataLoader:
    """Batched loader with background prefetch threads.

    Reference uses multiprocess workers + shared memory (python/paddle/io/
    dataloader/dataloader_iter.py — verify); here worker threads prefetch
    into a bounded queue — numpy decode releases the GIL for the common
    cases, and the jitted step keeps the TPU busy while the next batch
    collates. num_workers>0 enables prefetch; 0 is fully synchronous."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.use_shared_memory = use_shared_memory
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self.prefetch_factor = max(2, prefetch_factor)
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _fetch(self, indices):
        return self.collate_fn([self.dataset[i] for i in indices])

    def _iter_iterable(self):
        batch = []
        for item in self.dataset:
            batch.append(item)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def __iter__(self):
        if self._iterable_mode:
            yield from self._iter_iterable()
            return
        if self.num_workers == 0:
            for indices in self.batch_sampler:
                yield self._fetch(indices)
            return
        if self.use_shared_memory and self._shm_usable():
            yield from self._iter_multiprocess()
        else:
            yield from self._iter_prefetch()

    def _shm_usable(self):
        import multiprocessing
        if multiprocessing.get_start_method(allow_none=True) not in (
                None, "fork"):
            return False  # dataset state must arrive in workers via fork
        from ..core import native_available
        return native_available()

    def _iter_multiprocess(self):
        """Forked worker processes; batches return through per-worker C++
        shared-memory rings (pickled numpy, one memcpy per side).

        Worker w owns batches w, w+nw, ... and its own ring, so the parent
        always pops exactly the ring that holds the next batch in order —
        no reorder buffer, and memory is bounded by nw ring capacities
        (a full ring back-pressures its worker)."""
        import multiprocessing
        import os
        import pickle

        from ..core.native_api import ShmQueue

        batches = list(self.batch_sampler)
        if not batches:
            return
        capacity = 32 << 20
        base = f"pt_dl_{os.getpid()}_{id(self) & 0xffffff}"
        queues = [ShmQueue(f"{base}_{w}", capacity=capacity, create=True)
                  for w in range(self.num_workers)]
        ctx = multiprocessing.get_context("fork")

        def worker_main(worker_id):
            global _worker_info
            _worker_info = _WorkerInfo(num_workers=self.num_workers,
                                       id=worker_id, dataset=self.dataset)
            if self.worker_init_fn is not None:
                self.worker_init_fn(worker_id)
            wq = ShmQueue(f"{base}_{worker_id}", capacity=capacity,
                          create=False)
            try:
                for i in range(worker_id, len(batches), self.num_workers):
                    try:
                        # raw samples only — collation happens in the
                        # parent so the forked child never touches jax
                        # (a child initialising the exclusive TPU client
                        # would wedge the chip)
                        data = [self.dataset[j] for j in batches[i]]
                        payload = pickle.dumps(
                            data, protocol=pickle.HIGHEST_PROTOCOL)
                    except Exception as e:  # surface in parent
                        payload = pickle.dumps(e)
                    if len(payload) + 8 > capacity:
                        payload = pickle.dumps(ValueError(
                            f"batch {i} ({len(payload)}B) exceeds the "
                            f"shared-memory ring capacity ({capacity}B); "
                            "lower batch_size or pass "
                            "use_shared_memory=False"))
                    wq.put(payload)
            finally:
                wq.close()

        procs = [ctx.Process(target=worker_main, args=(w,), daemon=True)
                 for w in range(self.num_workers)]
        for p in procs:
            p.start()
        # paddle contract: timeout=0 means block indefinitely — but a dead
        # worker must raise, not hang, so poll in slices and check liveness
        deadline = self.timeout if self.timeout else None
        try:
            for i in range(len(batches)):
                w = i % self.num_workers
                waited = 0.0
                while True:
                    slice_s = 5.0 if deadline is None \
                        else min(5.0, max(0.01, deadline - waited))
                    try:
                        data = pickle.loads(
                            queues[w].get(timeout=slice_s))
                        break
                    except TimeoutError:
                        waited += slice_s
                        if procs[w].exitcode not in (None, 0):
                            raise RuntimeError(
                                f"DataLoader worker {w} died with exit "
                                f"code {procs[w].exitcode} (killed/OOM?)"
                            ) from None
                        if deadline is not None and waited >= deadline:
                            raise
                if isinstance(data, Exception):
                    raise data
                yield self.collate_fn(data)
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join(timeout=5)
            for q in queues:
                q.close()

    def _iter_prefetch(self):
        q: queue.Queue = queue.Queue(self.num_workers * self.prefetch_factor)
        batches = list(self.batch_sampler)
        stop = threading.Event()
        seq_lock = threading.Lock()
        results: dict = {}
        next_submit = [0]
        _SENTINEL = object()

        def worker():
            while not stop.is_set():
                with seq_lock:
                    i = next_submit[0]
                    if i >= len(batches):
                        return
                    next_submit[0] += 1
                try:
                    data = self._fetch(batches[i])
                except Exception as e:  # surface in main thread
                    data = e
                results[i] = data
                q.put(i)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self.num_workers)]
        for t in threads:
            t.start()
        try:
            emitted = 0
            buffered: dict = {}
            next_emit = 0
            while emitted < len(batches):
                if next_emit in buffered:
                    data = buffered.pop(next_emit)
                else:
                    i = q.get()
                    data = results.pop(i)
                    if i != next_emit:
                        buffered[i] = data
                        continue
                if isinstance(data, Exception):
                    raise data
                yield data
                emitted += 1
                next_emit += 1
        finally:
            stop.set()


def device_prefetch(loader, size=2, sharding=None):
    """Wrap a batch iterator so batches are transferred to device ``size``
    steps ahead of consumption (reference: the DataLoader buffer reader /
    pin-memory double buffering — python/paddle/io/dataloader — verify).

    On TPU, jax device transfers are async: enqueueing the NEXT batch's
    host->device copy before the current step finishes overlaps input IO
    with compute. ``sharding`` (e.g. NamedSharding(mesh, P("dp"))) places
    each leaf directly into its dp-sharded layout."""
    import collections as _c

    import jax as _jax

    from ..tensor import Tensor as _T

    def _put(x):
        v = x._value if isinstance(x, _T) else x
        v = _jax.device_put(v, sharding) if sharding is not None \
            else _jax.device_put(v)
        return _T(v) if isinstance(x, _T) else v

    def _transfer(batch):
        return _jax.tree.map(_put, batch,
                             is_leaf=lambda x: isinstance(x, _T))

    if size <= 0:
        # no prefetch: transfer-and-yield lockstep
        for batch in loader:
            yield _transfer(batch)
        return
    queue = _c.deque()
    for batch in loader:
        # drain BEFORE transferring: at most ``size`` batches are ever
        # in flight (append-then-check kept size+1 device buffers live)
        if len(queue) >= size:
            yield queue.popleft()
        queue.append(_transfer(batch))
    while queue:
        yield queue.popleft()


__all__.append("device_prefetch")


class ConcatDataset(Dataset):
    """Concatenate map-style datasets (reference: io.ConcatDataset)."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError("ConcatDataset needs at least one dataset")
        self._cum = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self._cum[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        if not 0 <= idx < len(self):
            raise IndexError(
                f"index {idx} out of range for ConcatDataset of length "
                f"{len(self)}")
        di = bisect.bisect_right(self._cum, idx)
        prev = self._cum[di - 1] if di else 0
        return self.datasets[di][idx - prev]


class SubsetRandomSampler(Sampler):
    """Sample the given indices in random order (reference parity).
    ``generator`` may be a numpy Generator/RandomState or an int seed;
    None draws from the global stream."""

    def __init__(self, indices, generator=None):
        self.indices = list(indices)
        if isinstance(generator, (int, np.integer)):
            generator = np.random.default_rng(int(generator))
        self.generator = generator

    def __iter__(self):
        rng = self.generator if self.generator is not None else np.random
        order = rng.permutation(len(self.indices))
        return iter([self.indices[i] for i in order])

    def __len__(self):
        return len(self.indices)


__all__ += ["ConcatDataset", "SubsetRandomSampler"]
