"""AMP: auto_cast / GradScaler / decorate (reference: python/paddle/amp/
— verify).

TPU-native design: bf16-first. O1 auto_cast casts white-listed op inputs
(matmul/conv/einsum) to the low dtype at dispatch; O2 ``decorate`` casts
parameters wholesale and keeps fp32 master weights in the optimizer
(multi_precision). GradScaler exists for fp16 parity; with bf16 it is an
identity (no loss scaling needed — documented divergence from CUDA fp16)."""
from __future__ import annotations

import contextlib

import jax.numpy as jnp
import numpy as np

from .. import framework
from ..framework import convert_dtype
from ..tensor import Tensor

__all__ = ["auto_cast", "amp_guard", "decorate", "amp_decorate", "GradScaler",
           "is_auto_cast_enabled", "get_amp_dtype", "op_amp_role",
           "white_cast", "black_cast"]

# op lists come from the op-metadata registry (reference: amp_lists.py
# keyed off the op YAML table — here ops/registry.py is that table).
# Live views: ops registered after import still affect casting.
from ..ops.registry import amp_black_list, amp_white_list


def __getattr__(name):
    if name == "WHITE_LIST":
        return amp_white_list()
    if name == "BLACK_LIST":
        return amp_black_list()
    raise AttributeError(name)


def is_auto_cast_enabled() -> bool:
    st = framework.state().amp_stack
    return bool(st) and st[-1]["enable"]


def op_amp_role(op_name):
    """Role of an op in the innermost enabled auto_cast scope:
    ``"white"`` (run in the low dtype), ``"black"`` (keep fp32),
    ``"neutral"`` (no list — follow inputs), or ``None`` (no scope).
    ``op_name`` may be a str or a tuple of alias names (e.g. ``mm``
    dispatches as the ``matmul`` op type; black-listing either name
    must catch it). Precedence: the scope's custom_white_list beats
    every black entry (user override of a framework-black op), then
    black (custom or framework), then white."""
    st = framework.state().amp_stack
    if not st or not st[-1]["enable"]:
        return None
    top = st[-1]
    names = (op_name,) if isinstance(op_name, str) else tuple(op_name)
    if any(n in top["custom_white"] for n in names):
        return "white"
    if any(n in top["black"] for n in names):
        return "black"
    if any(n in top["white"] for n in names):
        return "white"
    return "neutral"


def get_amp_dtype(op_name=None):
    """Low dtype of the innermost enabled auto_cast scope, or None.
    With ``op_name`` (str or alias tuple), honors the scope's
    custom_black_list: an op the user black-listed gets None (kept in
    fp32) even if the framework white-lists it."""
    st = framework.state().amp_stack
    if not st or not st[-1]["enable"]:
        return None
    if op_name is not None and op_amp_role(op_name) == "black":
        return None
    return st[-1]["dtype"]


def white_cast(*arrays, op_name=None):
    """Cast op inputs to the AMP low dtype (white-listed op callsites).
    The single cast implementation — matmul-class ops in ops/math.py and
    nn/functional.py all route through this so black-list overrides and
    non-float passthrough behave identically everywhere. A
    user-black-listed op UPCASTS low-precision inputs to fp32 (the op
    must run fp32 even over O2-decorated bf16 weights), it doesn't just
    skip the downcast."""
    d = get_amp_dtype(op_name)
    if d is None:
        if op_name is not None and is_auto_cast_enabled() and \
                op_amp_role(op_name) == "black":
            out = tuple(a.astype(jnp.float32) if hasattr(a, "dtype") and
                        a.dtype in (jnp.float16, jnp.bfloat16) else a
                        for a in arrays)
            return out if len(out) > 1 else out[0]
        return arrays if len(arrays) > 1 else arrays[0]
    out = tuple(a.astype(d) if hasattr(a, "dtype") and
                jnp.issubdtype(a.dtype, jnp.floating) else a
                for a in arrays)
    return out if len(out) > 1 else out[0]


def black_cast(*arrays, op_name=None):
    """Cast op inputs up to fp32 (black-listed op callsites). With
    ``op_name``, only upcasts when the scope resolves the op to black —
    a custom_white_list entry for a framework-black op (user says "run
    my softmax in bf16") suppresses the upcast."""
    if get_amp_dtype() is None:
        return arrays if len(arrays) > 1 else arrays[0]
    if op_name is not None and op_amp_role(op_name) != "black":
        return arrays if len(arrays) > 1 else arrays[0]
    out = tuple(a.astype(jnp.float32) if hasattr(a, "dtype") and
                a.dtype in (jnp.float16, jnp.bfloat16) else a
                for a in arrays)
    return out if len(out) > 1 else out[0]


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    d = convert_dtype(dtype)
    framework.state().amp_stack.append(
        {"enable": enable, "dtype": d, "level": level,
         "custom_white": set(custom_white_list or ()),
         "white": set(custom_white_list or ()) | amp_white_list(),
         "black": set(custom_black_list or ()) | amp_black_list()})
    try:
        yield
    finally:
        framework.state().amp_stack.pop()


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None, master_grad=False,
             excluded_layers=None):
    """O2: cast model params to the low dtype; optimizer keeps fp32 masters
    via multi_precision."""
    from ..nn.layer import Layer
    d = convert_dtype(dtype)
    model_list = models if isinstance(models, (list, tuple)) else [models]
    if level == "O2":
        excluded = []
        if excluded_layers:
            for l in (excluded_layers if isinstance(
                    excluded_layers, (list, tuple)) else [excluded_layers]):
                excluded.extend(
                    [l] if isinstance(l, Layer) else
                    [s for m in model_list for s in m.sublayers(True)
                     if isinstance(s, l)])
        excluded_ids = {id(p) for l in excluded for p in l.parameters()}
        from ..nn.norm import _BatchNormBase, LayerNorm
        for m in model_list:
            for sub in m.sublayers(include_self=True):
                if isinstance(sub, (_BatchNormBase, LayerNorm)):
                    excluded_ids.update(id(p) for p in
                                        sub._parameters.values()
                                        if p is not None)
            for p in m.parameters():
                if id(p) not in excluded_ids and jnp.issubdtype(
                        p._value.dtype, jnp.floating):
                    p._update_value(p._value.astype(d))
    if optimizers is None:
        return models if len(model_list) > 1 else model_list[0]
    opt_list = optimizers if isinstance(optimizers, (list, tuple)) \
        else [optimizers]
    for opt in opt_list:
        opt._multi_precision = True if master_weight is not False else False
    if isinstance(models, (list, tuple)) or isinstance(optimizers,
                                                       (list, tuple)):
        return model_list, opt_list
    return model_list[0], opt_list[0]


amp_decorate = decorate


class GradScaler:
    """Dynamic loss scaling (reference: python/paddle/amp/grad_scaler.py
    — verify). With bf16 (TPU default) scaling is a no-op passthrough."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def scale(self, loss):
        if not self._enable:
            return loss
        from ..ops.math import scale as scale_op
        return scale_op(loss, self._scale)

    def unscale_(self, optimizer):
        if not self._enable or self._unscaled:
            return
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._param_list:
            if p.grad is None:
                continue
            g = p.grad._value.astype(jnp.float32) * inv
            if bool(jnp.any(~jnp.isfinite(g))):
                found = True
            p.grad._update_value(g.astype(p.grad._value.dtype))
        self._found_inf = found
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not self._unscaled:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._update_scale()
        self._unscaled = False

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)
        self.update()

    def update(self):
        pass  # scale bookkeeping happens in step(); kept for API parity

    def _update_scale(self):
        if not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_count": self._good_steps,
                "decr_count": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("incr_count", 0)
        self._bad_steps = state.get("decr_count", 0)

    set_state_dict = load_state_dict

from . import debugging  # noqa: F401


def is_bfloat16_supported(device=None):
    """bf16 is the native TPU matmul dtype (reference:
    paddle.amp.is_bfloat16_supported — verify)."""
    return True


def is_float16_supported(device=None):
    """fp16 compute is emulated on TPU (XLA upcasts); supported as a
    storage dtype."""
    import jax
    return jax.default_backend() != "tpu" or True
