"""paddle.amp.debugging parity (reference:
python/paddle/amp/debugging.py — check_numerics, DebugMode,
enable/disable_operator_stats_collection, collect_operator_stats —
verify).

TPU-native design: every eager op flows through ``tensor.apply_op``, so
operator stats are one hook there (counting calls per op and per output
dtype — the reference's per-kernel low-precision summary); check_numerics
is a host-side nan/inf assertion on the materialized value.
"""
from __future__ import annotations

import contextlib
import enum
from collections import Counter

import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor

__all__ = ["DebugMode", "check_numerics", "enable_operator_stats_collection",
           "disable_operator_stats_collection", "collect_operator_stats",
           "TensorCheckerConfig", "enable_tensor_checker",
           "disable_tensor_checker"]


class DebugMode(enum.Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 2


_STATS: Counter = Counter()
_DTYPE_STATS: Counter = Counter()
_COLLECTING = [False]
_CHECKER = [None]   # active TensorCheckerConfig or None


def _op_hook(fn, outputs):
    """Called by tensor.apply_op for every dispatched op (when enabled)."""
    if _COLLECTING[0]:
        name = getattr(fn, "__qualname__", None) or repr(fn)
        if name == "<lambda>":
            name = f"{getattr(fn, '__module__', '?')}.<lambda>"
        _STATS[name] += 1
        for o in outputs:
            try:
                _DTYPE_STATS[str(jnp.dtype(o.dtype))] += 1
            except Exception:
                pass
    cfg = _CHECKER[0]
    if cfg is not None:
        name = getattr(fn, "__qualname__", "op")
        if cfg._wants(name):
            for o in outputs:
                if jnp.issubdtype(jnp.dtype(o.dtype), jnp.floating):
                    check_numerics(o, op_type=name,
                                   debug_mode=cfg.debug_mode)


def enable_operator_stats_collection():
    _STATS.clear()
    _DTYPE_STATS.clear()
    _COLLECTING[0] = True
    _install()


def disable_operator_stats_collection():
    _COLLECTING[0] = False
    _print_stats()
    _maybe_uninstall()


def _print_stats():
    print("<------------------- op list ------------------->")
    for name, cnt in _STATS.most_common():
        print(f"  {name:60s} {cnt}")
    print("<----------------- dtype counts ----------------->")
    for dt, cnt in sorted(_DTYPE_STATS.items()):
        print(f"  {dt:12s} {cnt}")


@contextlib.contextmanager
def collect_operator_stats():
    """Context manager: collect + print op/dtype stats for the block."""
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def check_numerics(tensor, op_type="", var_name="",
                   debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT):
    """Raise (or warn) when the tensor contains NaN/Inf (reference:
    check_numerics op). Host-side: forces materialization."""
    raw = tensor._value if isinstance(tensor, Tensor) else tensor
    if not jnp.issubdtype(jnp.dtype(raw.dtype), jnp.floating):
        return tensor
    v = np.asarray(raw)
    if not np.issubdtype(v.dtype, np.floating):
        # bfloat16/fp8 (ml_dtypes): lift to float32 for the host checks
        v = v.astype(np.float32)
    bad_nan = int(np.isnan(v).sum())
    bad_inf = int(np.isinf(v).sum())
    if bad_nan or bad_inf:
        msg = (f"check_numerics: {op_type or 'tensor'} {var_name} has "
               f"{bad_nan} NaN and {bad_inf} Inf values "
               f"(shape {list(v.shape)})")
        if debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
            raise RuntimeError(msg)
        import warnings
        warnings.warn(msg, stacklevel=2)
    return tensor


class TensorCheckerConfig:
    """reference parity: enable_tensor_checker(config) turns on per-op
    output checking. checked_op_list / skipped_op_list filter by
    substring match on the dispatched op's qualified name; output_dir,
    debug_step, and stack_height_limit are accepted for signature parity
    but unsupported (a warning says so)."""

    def __init__(self, enable=True,
                 debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None,
                 skipped_op_list=None, debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.checked_op_list = list(checked_op_list or [])
        self.skipped_op_list = list(skipped_op_list or [])
        if output_dir or debug_step:
            import warnings
            warnings.warn(
                "TensorCheckerConfig: output_dir/debug_step are not "
                "supported here (checks raise/warn inline)", stacklevel=2)

    def _wants(self, name):
        if any(p in name for p in self.skipped_op_list):
            return False
        if self.checked_op_list:
            return any(p in name for p in self.checked_op_list)
        return True


def enable_tensor_checker(checker_config):
    if getattr(checker_config, "enable", True):
        _CHECKER[0] = checker_config
        _install()


def disable_tensor_checker():
    _CHECKER[0] = None
    _maybe_uninstall()


def _install():
    from .. import tensor as _t
    _t._OP_HOOK[0] = _op_hook


def _maybe_uninstall():
    """Drop the hot-path hook entirely when both features are off —
    eager dispatch must pay nothing for a one-off debug session."""
    if not _COLLECTING[0] and _CHECKER[0] is None:
        from .. import tensor as _t
        _t._OP_HOOK[0] = None
