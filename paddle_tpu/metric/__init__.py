"""Metrics (reference: python/paddle/metric/metrics.py — verify)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def accuracy(input, label, k=1, correct=None, total=None):
    import jax.numpy as jnp
    from ..tensor import apply_op

    def f(pred, lab):
        topk = jnp.argsort(-pred, axis=-1)[..., :k]
        lab_ = lab.reshape(lab.shape[0], -1)[:, :1]
        hit = jnp.any(topk == lab_, axis=-1)
        return jnp.mean(hit.astype(jnp.float32))
    return apply_op(f, input, label)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label):
        p = np.asarray(pred._value if isinstance(pred, Tensor) else pred)
        l = np.asarray(label._value if isinstance(label, Tensor) else label)
        l = l.reshape(l.shape[0], -1)[:, :1]
        maxk = max(self.topk)
        topk = np.argsort(-p, axis=-1)[..., :maxk]
        corrects = (topk == l)
        return Tensor(jnp.asarray(corrects.astype(np.float32)))

    def update(self, correct):
        c = np.asarray(correct._value if isinstance(correct, Tensor)
                       else correct)
        for i, k in enumerate(self.topk):
            self.total[i] += float(c[..., :k].sum())
            self.count[i] += c.shape[0]
        return self.accumulate()

    def accumulate(self):
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (np.asarray(preds._value if isinstance(preds, Tensor)
                        else preds) > 0.5).astype(int).reshape(-1)
        l = np.asarray(labels._value if isinstance(labels, Tensor)
                       else labels).astype(int).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (np.asarray(preds._value if isinstance(preds, Tensor)
                        else preds) > 0.5).astype(int).reshape(-1)
        l = np.asarray(labels._value if isinstance(labels, Tensor)
                       else labels).astype(int).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._value if isinstance(labels, Tensor)
                       else labels).reshape(-1)
        pos_prob = p[:, 1] if p.ndim == 2 and p.shape[1] == 2 else \
            p.reshape(-1)
        bins = np.minimum((pos_prob * self.num_thresholds).astype(int),
                          self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        area = 0.0
        pos = neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            p_, n_ = self._stat_pos[i], self._stat_neg[i]
            area += n_ * (pos + p_ / 2.0)
            pos += p_
            neg += n_
        return area / (tot_pos * tot_neg)

    def name(self):
        return self._name
