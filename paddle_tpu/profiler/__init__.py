"""Profiler (reference: python/paddle/profiler/,
paddle/fluid/platform/profiler/ RecordEvent/CUPTI tracer — verify).

TPU-native design: device tracing delegates to ``jax.profiler``
(XProf/TensorBoard, perfetto); host spans are our own RecordEvent ring
writing chrome-trace JSON, merged with the jax trace directory."""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from enum import Enum
from typing import Callable, Iterable, Optional

__all__ = ["Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "load_profiler_result",
           "SummaryView"]


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1      # parity alias — maps to the TPU device tracer
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SummaryView(Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6


_EVENTS: list = []
_EVENTS_LOCK = threading.Lock()
_ACTIVE = [False]


def _tracer():
    """The C++ host tracer (paddle_tpu.core libptcore); None when the
    native library is unavailable — spans then use the Python path."""
    from ..core.native_api import global_tracer
    t = global_tracer()
    return t if t.is_native else None


class RecordEvent:
    """Host span (reference: paddle.profiler.RecordEvent / C++ RecordEvent
    — verify). Usable as context manager or begin()/end()."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._begin = None

    def begin(self):
        t = _tracer()
        if t is not None:
            t.begin(self.name)
            self._begin = "native"
            return
        self._begin = time.perf_counter_ns()

    def end(self):
        if self._begin == "native":
            t = _tracer()
            if t is not None:
                t.end()
            return
        if self._begin is None or not _ACTIVE[0]:
            return
        now = time.perf_counter_ns()
        with _EVENTS_LOCK:
            _EVENTS.append({"name": self.name, "ph": "X", "pid": os.getpid(),
                            "tid": threading.get_ident(),
                            "ts": self._begin / 1000.0,
                            "dur": (now - self._begin) / 1000.0})

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def make_scheduler(closed: int = 0, ready: int = 0, record: int = 1,
                   repeat: int = 0, skip_first: int = 0):
    total = closed + ready + record

    def scheduler(step: int):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * total:
            return ProfilerState.CLOSED
        pos = s % total
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == total - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"worker_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}.json")
        with open(path, "w") as f:
            json.dump({"traceEvents": prof._drain_events()}, f)
        prof._last_export = path
    return handler


def load_profiler_result(path):
    with open(path) as f:
        return json.load(f)


class Profiler:
    def __init__(self, targets: Optional[Iterable] = None, scheduler=None,
                 on_trace_ready: Optional[Callable] = None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 emit_nvtx=False, custom_device_types=None, with_flops=False):
        self.targets = list(targets or [ProfilerTarget.CPU,
                                        ProfilerTarget.TPU])
        if isinstance(scheduler, tuple):
            start, end = scheduler
            scheduler = make_scheduler(closed=start, ready=0,
                                       record=end - start, repeat=1)
        self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._jax_trace_dir = None
        self._jax_active = False
        self._last_export = None

    # -- device tracer ------------------------------------------------------
    def _start_device_trace(self):
        if self.timer_only or self._jax_active:
            return
        import tempfile
        import jax
        want_device = any(t in (ProfilerTarget.GPU, ProfilerTarget.TPU)
                          for t in self.targets)
        if want_device:
            self._jax_trace_dir = tempfile.mkdtemp(prefix="pdtpu_prof_")
            try:
                jax.profiler.start_trace(self._jax_trace_dir)
                self._jax_active = True
            except Exception:
                self._jax_active = False

    def _stop_device_trace(self):
        if self._jax_active:
            import jax
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._jax_active = False

    def _drain_events(self):
        with _EVENTS_LOCK:
            ev = list(_EVENTS)
            _EVENTS.clear()
        t = _tracer()
        if t is not None and t.event_count():
            import tempfile
            with tempfile.NamedTemporaryFile(suffix=".json",
                                             delete=False) as f:
                tmp = f.name
            try:
                t.dump(tmp, pid=os.getpid())
                with open(tmp) as f:
                    ev.extend(json.load(f).get("traceEvents", []))
                t.clear()
            finally:
                os.unlink(tmp)
        return ev

    # -- lifecycle ----------------------------------------------------------
    @staticmethod
    def _recording(state) -> bool:
        return state in (ProfilerState.RECORD,
                         ProfilerState.RECORD_AND_RETURN)

    def _arm_host_ring(self, on: bool):
        """The host-span ring (and the native tracer) record iff the
        scheduler state says so. ``start()`` used to set the ring
        unconditionally — host spans recorded through CLOSED warmup
        steps — and CLOSED→RECORD transitions in ``step()`` never
        re-armed it; both directions are regression-pinned in
        tests/test_observability.py."""
        _ACTIVE[0] = on
        t = _tracer()
        if t is not None:
            t.enable(on)

    def start(self):
        self._state = self.scheduler(self._step) if self.scheduler else \
            ProfilerState.RECORD
        self._arm_host_ring(self._recording(self._state))
        if self._recording(self._state):
            self._start_device_trace()

    def stop(self):
        self._stop_device_trace()
        self._arm_host_ring(False)
        if self.on_trace_ready:
            self.on_trace_ready(self)

    def step(self, num_samples=None):
        self._step += 1
        if self.scheduler:
            new_state = self.scheduler(self._step)
            self._arm_host_ring(self._recording(new_state))
            if self._recording(new_state) and not self._jax_active:
                self._start_device_trace()
            elif new_state == ProfilerState.CLOSED and self._jax_active:
                self._stop_device_trace()
            if self._state == ProfilerState.RECORD_AND_RETURN and \
                    self.on_trace_ready:
                self.on_trace_ready(self)
            self._state = new_state

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def export(self, path, format="json"):
        # missing parent directories are created, not a crash — bench
        # children and trace handlers export into per-run directories
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump({"traceEvents": self._drain_events()}, f)
        self._last_export = path

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", views=None, print_table=True):
        """Aggregate the drained host spans. Returns ``(table, agg)``
        — the rendered table plus the per-name
        ``{"calls", "total_us"}`` dict — and only prints when
        ``print_table`` (headless/bench callers want the numbers, not
        stdout noise)."""
        ev = self._drain_events()
        agg: dict = {}
        for e in ev:
            if e.get("ph") != "X":
                continue
            a = agg.setdefault(e["name"], {"calls": 0, "total_us": 0.0})
            a["calls"] += 1
            a["total_us"] += e.get("dur", 0.0)
        lines = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>12}"]
        for name, a in sorted(agg.items(), key=lambda kv: -kv[1]["total_us"]):
            lines.append(f"{name:<40}{a['calls']:>8}"
                         f"{a['total_us'] / 1000:>12.3f}"
                         f"{a['total_us'] / 1000 / a['calls']:>12.3f}")
        table = "\n".join(lines)
        if print_table:
            print(table)
        return table, agg
