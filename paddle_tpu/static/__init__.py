"""paddle_tpu.static — static-graph execution mode.

Reference parity: python/paddle/static/ — Program/Executor/data,
program_guard, optimizer.minimize building the backward program
(paddle/fluid/framework ProgramDesc + new_executor InterpreterCore —
verify).

TPU-native design: "building the program" is deferred op recording —
under ``paddle.enable_static()`` every op call infers output shapes with
``jax.eval_shape`` and records its producer instead of computing
(tensor.py ``_apply_op_static``). ``Executor.run`` walks the recorded
DAG from the fetches to the ``data`` placeholders, closes it into ONE
pure function, and jit-compiles it — the whole static program becomes a
single XLA executable. ``optimizer.minimize(loss)`` marks the program as
a train program; Executor.run then compiles loss+grads+update as one
donated step and writes updated parameters back.
"""
from __future__ import annotations

import contextlib
import os
from typing import Dict, Optional, Sequence

import numpy as np

from .. import framework
from ..framework import convert_dtype
from ..tensor import Parameter, Tensor

__all__ = ["InputSpec", "Program", "Executor", "data", "program_guard",
           "default_main_program", "default_startup_program",
           "name_scope", "device_guard", "amp", "CompiledProgram",
           "global_scope", "cpu_places", "append_backward", "gradients",
           "save_inference_model", "load_inference_model"]


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(-1 if s is None else s for s in shape)
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        import jax.numpy as jnp
        return cls(tuple(tensor.shape), jnp.dtype(tensor.dtype).name, name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, str(ndarray.dtype), name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, " \
               f"name={self.name})"


class Program:
    """A recorded static graph: feed placeholders + (after minimize) the
    training objective. The op DAG itself lives on the fetched tensors'
    producer records."""

    def __init__(self):
        self.placeholders: Dict[str, Tensor] = {}
        self.random_seed = 0
        self._train: Optional[tuple] = None   # (loss Tensor, optimizer)
        self._exec_cache: dict = {}

    def clone(self, for_test=False):
        p = Program()
        p.placeholders = dict(self.placeholders)
        p._train = None if for_test else self._train
        return p

    def global_block(self):
        return self

    def __repr__(self):
        return (f"Program(placeholders={list(self.placeholders)}, "
                f"train={'yes' if self._train else 'no'})")


_default_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _default_program


def default_startup_program() -> Program:
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _default_program, _startup_program
    prev_m, prev_s = _default_program, _startup_program
    _default_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _default_program, _startup_program = prev_m, prev_s


def data(name: str, shape, dtype="float32", lod_level=0) -> Tensor:
    """Declare a feed placeholder in the current program. Unknown batch
    dims (None/-1) take the fed array's size at run time — each distinct
    shape compiles once (XLA static shapes)."""
    import jax
    shape = tuple(1 if (s is None or s == -1) else s for s in shape)
    t = Tensor(jax.ShapeDtypeStruct(shape, convert_dtype(dtype)),
               stop_gradient=True, name=name)
    t._static_src = None
    _default_program.placeholders[name] = t
    return t


def append_backward(loss, parameter_list=None, no_grad_set=None):
    """Kept for API parity — the backward program is derived inside
    Executor.run via jax.grad once minimize() records the loss."""
    return []


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Symbolic gradients of sum(targets) w.r.t. ``inputs`` (reference:
    paddle.static.gradients — verify). Returns one grad tensor per
    input; fetching it makes Executor.run differentiate the recorded
    program with jax.grad during replay."""
    import jax
    if target_gradients is not None:
        raise NotImplementedError(
            "static.gradients(target_gradients=...) is unsupported")
    tgts = tuple(targets) if isinstance(targets, (list, tuple)) \
        else (targets,)
    ins = list(inputs) if isinstance(inputs, (list, tuple)) else [inputs]
    outs = []
    for x in ins:
        g = Tensor(jax.ShapeDtypeStruct(tuple(x.shape), x._value.dtype),
                   stop_gradient=True,
                   name=f"{getattr(x, 'name', 'x')}@GRAD")
        g._static_src = None
        g._static_grad = (tgts, x)
        outs.append(g)
    return outs


def _mark_train(program: Program, loss: Tensor, optimizer) -> None:
    """Called by Optimizer.minimize under static mode."""
    program._train = (loss, optimizer)


def _replay(t, env, feeds_by_name):
    """Evaluate tensor `t` from its producer record (memoized in env)."""
    if id(t) in env:
        return env[id(t)]
    gsrc = getattr(t, "_static_grad", None)
    if gsrc is not None:           # a static.gradients() output
        import jax
        targets, wrt = gsrc
        xval = _replay(wrt, env, feeds_by_name)

        def scalar(xv):
            env2 = {id(wrt): xv}
            tot = None
            for tg in targets:
                s = _replay(tg, env2, feeds_by_name).sum()
                tot = s if tot is None else tot + s
            return tot

        val = jax.grad(scalar)(xval)
        env[id(t)] = val
        return val
    src = getattr(t, "_static_src", None)
    if src is None:
        val = feeds_by_name.get(t.name, t._value)
    else:
        skey = ("src", id(src))
        if skey not in env:
            ins = [_replay(i, env, feeds_by_name) for i in src.inputs]
            out = src.pure(*ins)
            env[skey] = out if src.multi else (out,)
        val = env[skey][t._out_index if src.multi else 0]
    env[id(t)] = val
    return val


class Executor:
    """Runs a recorded Program as one jitted XLA program (the
    reference's StandaloneExecutor role)."""

    def __init__(self, place=None):
        self.place = place

    def _feeds(self, feed):
        import jax.numpy as jnp
        return {n: jnp.asarray(v._value if isinstance(v, Tensor) else v)
                for n, v in (feed or {}).items()}

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list: Optional[Sequence] = None, return_numpy=True):
        import jax

        program = program or _default_program
        fetch_list = list(fetch_list or [])
        if isinstance(program, _LoadedInference):
            vals = [jnp_val for jnp_val in (
                self._feeds(feed)[n] for n in program.feed_names)]
            outs = program(*vals)
            if return_numpy:
                return [np.asarray(o) for o in outs]
            return [Tensor(o) for o in outs]
        if not fetch_list:
            return []
        if program._train is not None:
            return self._run_train(program, feed, fetch_list, return_numpy)

        def fn(feeds_by_name):
            env: dict = {}
            return [_replay(t, env, feeds_by_name) for t in fetch_list]

        key = (tuple(id(t) for t in fetch_list), "eval")
        jitted = program._exec_cache.get(key)
        if jitted is None:
            jitted = jax.jit(fn)
            program._exec_cache[key] = jitted
        outs = jitted(self._feeds(feed))
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]

    def _run_train(self, program, feed, fetch_list, return_numpy):
        import jax

        loss_t, opt = program._train
        params = {n: p for n, p in zip(opt._param_names, opt._param_list)
                  if not p.stop_gradient}
        lr_value = opt.get_lr()

        def forward(param_vals, feeds_by_name):
            env = {id(params[n]): v for n, v in param_vals.items()}
            loss = _replay(loss_t, env, feeds_by_name)
            fetches = [_replay(t, env, feeds_by_name) for t in fetch_list]
            return loss, fetches

        def step(param_vals, opt_state, feeds_by_name):
            (_, fetches), grads = jax.value_and_grad(
                forward, has_aux=True)(param_vals, feeds_by_name)
            new_params, new_state = opt.functional_update(
                param_vals, grads, opt_state, lr_value)
            return new_params, new_state, fetches

        key = (tuple(id(t) for t in fetch_list), "train")
        jitted = program._exec_cache.get(key)
        if jitted is None:
            jitted = jax.jit(step)
            program._exec_cache[key] = jitted
        param_vals = {n: p._value for n, p in params.items()}
        new_params, new_state, fetches = jitted(
            param_vals, opt.functional_state(), self._feeds(feed))
        for n, p in params.items():
            p._value = new_params[n]
        opt.load_functional_state(new_state)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    def close(self):
        pass


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program


class _LoadedInference:
    """Deserialized inference program: Executor.run recognizes it and
    calls the compiled StableHLO directly."""

    def __init__(self, exported, feed_names, n_fetch):
        self._exported = exported
        self.feed_names = list(feed_names)
        self.n_fetch = n_fetch

    def __call__(self, *feed_vals):
        return self._exported.call(*feed_vals)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Serialize the recorded static program feeds→fetches as StableHLO
    (reference: paddle.static.save_inference_model writes
    .pdmodel/.pdiparams — verify; here ONE portable artifact holds
    program + constants, the same contract as inference.export_model)."""
    import json as _json
    import jax

    feed_vars = list(feed_vars)
    fetch_vars = list(fetch_vars)

    def fn(*feed_vals):
        feeds_by_name = {v.name: val for v, val in
                         zip(feed_vars, feed_vals)}
        env: dict = {}
        return [_replay(t, env, feeds_by_name) for t in fetch_vars]

    specs = [jax.ShapeDtypeStruct(tuple(v.shape), v._value.dtype)
             for v in feed_vars]
    exported = jax.export.export(jax.jit(fn))(*specs)
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    with open(path_prefix + ".pdmeta", "w") as f:
        _json.dump({"feed_names": [v.name for v in feed_vars],
                    "n_fetch": len(fetch_vars)}, f)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Returns ``[program, feed_target_names, fetch_targets]`` as the
    reference does; pass the program to :meth:`Executor.run`."""
    import json as _json
    import jax

    with open(path_prefix + ".pdmodel", "rb") as f:
        exported = jax.export.deserialize(f.read())
    with open(path_prefix + ".pdmeta") as f:
        meta = _json.load(f)
    prog = _LoadedInference(exported, meta["feed_names"],
                            meta["n_fetch"])
    fetch_targets = list(range(meta["n_fetch"]))
    return [prog, prog.feed_names, fetch_targets]


def global_scope():
    return _default_program


def cpu_places(device_count=None):
    return ["cpu"]


@contextlib.contextmanager
def name_scope(prefix=None):
    import jax
    with jax.named_scope(prefix or "scope"):
        yield


@contextlib.contextmanager
def device_guard(device=None):
    yield


class amp:
    """paddle.static.amp namespace shim."""
    @staticmethod
    def decorate(*a, **k):
        raise NotImplementedError("use paddle_tpu.amp.decorate")

from . import nn  # noqa: F401  (static.nn helpers)
