"""paddle_tpu.static — static-graph API parity layer.

Reference: python/paddle/static/ (Program/Executor) — verify. TPU-native:
the "static graph" IS the jitted XLA program; this module provides
InputSpec and thin aliases so reference code importing paddle.static keeps
working. Program-construction APIs raise with guidance toward jit."""
from __future__ import annotations

import numpy as np

from ..framework import convert_dtype

__all__ = ["InputSpec", "default_main_program", "default_startup_program",
           "name_scope", "device_guard", "amp"]


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(-1 if s is None else s for s in shape)
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        import jax.numpy as jnp
        return cls(tuple(tensor.shape), jnp.dtype(tensor.dtype).name, name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, str(ndarray.dtype), name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, " \
               f"name={self.name})"


def default_main_program():
    raise NotImplementedError(
        "static Program API is not part of the TPU-native design; "
        "use paddle_tpu.jit.to_static (the jit boundary IS the program)")


default_startup_program = default_main_program


import contextlib


@contextlib.contextmanager
def name_scope(prefix=None):
    import jax
    with jax.named_scope(prefix or "scope"):
        yield


@contextlib.contextmanager
def device_guard(device=None):
    yield


class amp:
    """paddle.static.amp namespace shim."""
    @staticmethod
    def decorate(*a, **k):
        raise NotImplementedError("use paddle_tpu.amp.decorate")
