"""paddle.static.nn: static-graph layer helpers (reference:
python/paddle/static/nn/common.py — fc, conv2d, batch_norm, embedding,
… — verify).

TPU-native design and semantics:

- **Static mode (inside ``program_guard``)**: every call creates a NEW
  layer — build-once semantics, exactly the reference's (a static graph
  is constructed a single time; re-entering ``program_guard`` builds
  fresh parameters). Layers are attached to the CURRENT main Program, so
  their parameters live and die with it; ``all_parameters()`` returns
  the current program's.
- **Dygraph mode**: an explicit unique ``name=`` is REQUIRED (there is
  no graph to anchor identity to); repeated calls with the same name
  reuse the layer, and a STRUCTURAL config mismatch under a reused name
  (shapes, strides, norm axes, scale/shift) raises instead of silently
  returning the wrong layer. Parameter ATTRS (weight_attr/param_attr/
  bias_attr) apply at first creation only — they alter initialization,
  not the computation, so later calls reusing the name do not compare
  them.
- ``is_sparse`` is accepted for parity but has no effect: TPU gradients
  are dense (documented scope decision).
"""
from __future__ import annotations

import numpy as np

from .. import framework
from .. import nn as _nn

__all__ = ["fc", "conv2d", "batch_norm", "embedding", "sparse_embedding",
           "prelu", "layer_norm", "sequence_expand", "all_parameters"]

# dygraph-mode registry: (kind, name) -> (config, layer)
_NAMED: dict = {}


def _current_program():
    from . import default_main_program
    return default_main_program()


def _get_layer(name, kind, config, build):
    """Site-identity resolution per the module docstring."""
    if framework.in_static_mode():
        prog = _current_program()
        reg = prog.__dict__.setdefault("_static_nn_layers", [])
        layer = build()
        reg.append((name or f"{kind}_{len(reg)}", layer))
        return layer
    if name is None:
        raise ValueError(
            f"static.nn.{kind} in dygraph mode needs an explicit unique "
            "name= (outside a Program there is no graph site to anchor "
            "parameter identity to)")
    key = (kind, name)
    if key in _NAMED:
        old_config, layer = _NAMED[key]
        if old_config != config:
            raise ValueError(
                f"static.nn.{kind} name {name!r} reused with a different "
                f"configuration: {old_config} vs {config}")
        return layer
    layer = build()
    _NAMED[key] = (config, layer)
    return layer


def all_parameters(prefix=None):
    """Parameters of the current Program's helper-built layers (static
    mode; reference: Program.all_parameters), or of dygraph-named
    layers filtered by ``prefix``."""
    out = []
    if framework.in_static_mode():
        for name, layer in getattr(_current_program(),
                                   "_static_nn_layers", []):
            if prefix is None or name.startswith(prefix):
                out.extend(layer.parameters())
        return out
    for (kind, name), (_cfg, layer) in _NAMED.items():
        if prefix is None or name.startswith(prefix):
            out.extend(layer.parameters())
    return out


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Fully connected over the trailing dims (reference fc semantics:
    flatten from num_flatten_dims, then x @ W + b)."""
    flat_in = 1
    for d in x.shape[num_flatten_dims:]:
        flat_in *= int(d)
    layer = _get_layer(name, "fc", (flat_in, size), lambda: _nn.Linear(
        flat_in, size, weight_attr=weight_attr, bias_attr=bias_attr))
    from ..ops.manipulation import reshape
    lead = [int(d) for d in x.shape[:num_flatten_dims]]
    out = layer(reshape(x, lead + [flat_in]))
    if activation:
        out = getattr(_nn.functional, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None):
    cin = int(input.shape[1])
    cfg = (cin, num_filters, tuple(np.atleast_1d(filter_size).tolist()),
           stride, padding, dilation, groups)
    layer = _get_layer(name, "conv2d", cfg, lambda: _nn.Conv2D(
        cin, num_filters, filter_size, stride=stride, padding=padding,
        dilation=dilation, groups=groups, weight_attr=param_attr,
        bias_attr=bias_attr))
    out = layer(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-05,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               is_test=False, name=None):
    nch = int(input.shape[1] if data_layout == "NCHW"
              else input.shape[-1])
    cfg = (nch, momentum, epsilon, data_layout)
    layer = _get_layer(name, "batch_norm", cfg, lambda: _nn.BatchNorm2D(
        nch, momentum=momentum, epsilon=epsilon,
        weight_attr=param_attr, bias_attr=bias_attr,
        data_format=data_layout))
    # is_test is per-CALL, not a sticky mode flip on the shared layer
    was_training = layer.training
    if is_test:
        layer.eval()
    try:
        out = layer(input)
    finally:
        if is_test and was_training:
            layer.train()
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-05, param_attr=None, bias_attr=None, act=None,
               name=None):
    shape = tuple(int(d) for d in input.shape[begin_norm_axis:])
    layer = _get_layer(name, "layer_norm",
                       (shape, epsilon, bool(scale), bool(shift)),
                       lambda: _nn.LayerNorm(
                           list(shape), epsilon=epsilon,
                           weight_attr=param_attr if scale else False,
                           bias_attr=bias_attr if shift else False))
    out = layer(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32", name=None):
    if dtype not in ("float32", None):
        raise ValueError(
            f"static.nn.embedding dtype={dtype!r}: only float32 tables "
            "are supported (bf16 comes from AMP casting at use sites)")
    layer = _get_layer(name, "embedding", (tuple(size), padding_idx),
                       lambda: _nn.Embedding(
                           size[0], size[1], padding_idx=padding_idx,
                           weight_attr=param_attr))
    return layer(input)


def sparse_embedding(input, size, padding_idx=None, param_attr=None,
                     dtype="float32", name=None):
    """PS-mode sparse embedding (reference: static.nn.sparse_embedding
    feeds the parameter-server table). Delegates to
    distributed.ps.SparseEmbedding when a PS cluster is initialized,
    else degrades to a dense embedding."""
    from ..distributed import ps
    try:
        import paddle_tpu.distributed.rpc as _rpc
        in_cluster = ps.server_num() >= 1 and _rpc._AGENT is not None
    except Exception:
        in_cluster = False
    if in_cluster:
        emb = _get_layer(name, "sparse_embedding", tuple(size),
                         lambda: ps.SparseEmbedding(
                             name or f"sparse_emb_{size[0]}x{size[1]}",
                             size[0], size[1]))
        return emb(input)
    return embedding(input, size, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype, name=name)


class _ElemPrelu(_nn.Layer):
    """Per-element slopes (prelu mode='element'): one parameter per
    non-batch element, broadcast over the batch dim."""

    def __init__(self, shape, param_attr=None):
        super().__init__()
        from ..nn import initializer as I
        from ..param_attr import ParamAttr
        self.weight = self.create_parameter(
            shape, attr=ParamAttr._to_attr(param_attr),
            default_initializer=I.Constant(0.25))

    def forward(self, v):
        import jax.numpy as jnp
        from ..tensor import apply_op
        return apply_op(lambda a, w: jnp.where(a > 0, a, w[None] * a),
                        v, self.weight)


def prelu(x, mode="all", param_attr=None, name=None):
    if mode == "element":
        shape = tuple(int(d) for d in x.shape[1:])
        layer = _get_layer(name, "prelu", (mode, shape),
                           lambda: _ElemPrelu(shape, param_attr))
        return layer(x)
    if mode == "all":
        n_params = 1
    elif mode == "channel":
        n_params = int(x.shape[1])
    else:
        raise ValueError(
            f"prelu mode must be 'all', 'channel', or 'element', "
            f"got {mode!r}")
    layer = _get_layer(name, "prelu", (mode, n_params),
                       lambda: _nn.PReLU(num_parameters=n_params,
                                         weight_attr=param_attr))
    return layer(x)


def sequence_expand(x, y, ref_level=-1, name=None):
    raise NotImplementedError(
        "sequence_expand operates on LoD tensors, a CPU-era ragged "
        "format this TPU framework does not implement (documented scope "
        "decision: ragged sequences are expressed with padding + "
        "sequence_mask)")


class _DataNorm(_nn.Layer):
    """Global-statistics normalization (reference: paddle.static.nn.
    data_norm — verify): y = (x - mean) / stddev with mean/std derived
    from accumulated batch_size / batch_sum / batch_square_sum buffers.
    In train mode each forward folds the batch into the buffers with
    ``summary_decay_rate`` (the reference's summary update); eval mode
    normalizes with frozen stats."""

    def __init__(self, dim, epsilon=1e-4, slot_dim=-1,
                 summary_decay_rate=0.9999999,
                 enable_scale_and_shift=False):
        super().__init__()
        from ..tensor import to_tensor
        from ..nn import initializer as I
        self.epsilon = float(epsilon)
        self.decay = float(summary_decay_rate)
        self.register_buffer(
            "batch_size", to_tensor(np.full((dim,), 1e4, np.float32)))
        self.register_buffer(
            "batch_sum", to_tensor(np.zeros((dim,), np.float32)))
        self.register_buffer(
            "batch_square_sum",
            to_tensor(np.full((dim,), 1e4, np.float32)))
        self.scale_w = self.create_parameter(
            (dim,), default_initializer=I.Constant(1.0)) \
            if enable_scale_and_shift else None
        self.bias = self.create_parameter((dim,), is_bias=True) \
            if enable_scale_and_shift else None

    def forward(self, x):
        import jax.numpy as jnp
        from .. import ops
        if self.training and framework.in_static_mode():
            import warnings
            warnings.warn(
                "static-mode data_norm normalizes with FROZEN summary "
                "stats (the replay graph cannot mutate buffers); train "
                "the stats in dygraph mode or feed pre-computed "
                "summaries", stacklevel=2)
        if self.training and not framework.in_static_mode():
            # summary update (no tape): buffers decay, batch folds in
            xv = x._value
            n = float(xv.shape[0])
            self.batch_size._update_value(
                self.batch_size._value * self.decay + n)
            self.batch_sum._update_value(
                self.batch_sum._value * self.decay + jnp.sum(xv, 0))
            self.batch_square_sum._update_value(
                self.batch_square_sum._value * self.decay
                + jnp.sum(xv * xv, 0))
        mean = ops.divide(self.batch_sum, self.batch_size)
        var = ops.subtract(ops.divide(self.batch_square_sum,
                                      self.batch_size),
                           ops.multiply(mean, mean))
        scale = ops.rsqrt(ops.add(var, ops.scale(
            ops.ones_like(var), self.epsilon)))
        out = ops.multiply(ops.subtract(x, mean), scale)
        if self.scale_w is not None:
            out = ops.add(ops.multiply(out, self.scale_w), self.bias)
        return out


def data_norm(input, act=None, epsilon=1e-4, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    dim = int(input.shape[-1])
    layer = _get_layer(name, "data_norm",
                       (dim, epsilon, bool(enable_scale_and_shift),
                        summary_decay_rate, slot_dim),
                       lambda: _DataNorm(
                           dim, epsilon=epsilon, slot_dim=slot_dim,
                           summary_decay_rate=summary_decay_rate,
                           enable_scale_and_shift=enable_scale_and_shift))
    out = layer(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


__all__ += ["data_norm"]
