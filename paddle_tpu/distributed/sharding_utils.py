"""Placement helpers: put model params / batches onto a mesh.

TPU-native core of fleet.distributed_model: parameters carry
``_sharding_spec`` (set by TP layers, FSDP annotation, or shard_tensor);
this module materializes those specs as NamedSharding placements so jitted
steps inherit them (GSPMD then propagates through the whole program)."""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..nn.layer import Layer
from ..tensor import Tensor

__all__ = ["place_model", "shard_batch", "replicate", "filter_spec"]


def filter_spec(spec: Optional[P], mesh: Mesh, ndim: int) -> P:
    """Drop axes the mesh doesn't have; default replicated."""
    if spec is None:
        return P()
    axes = set(mesh.axis_names)
    out = []
    for s in tuple(spec):
        if s is None:
            out.append(None)
        elif isinstance(s, tuple):
            kept = tuple(a for a in s if a in axes)
            out.append(kept if kept else None)
        else:
            out.append(s if s in axes else None)
    return P(*out)


def _divisible(shape, spec: P, mesh: Mesh) -> bool:
    for dim, s in zip(shape, tuple(spec)):
        if s is None:
            continue
        names = s if isinstance(s, tuple) else (s,)
        total = int(np.prod([mesh.shape[a] for a in names]))
        if total and dim % total != 0:
            return False
    return True


def place_model(model: Layer, mesh: Mesh, shard_specs: bool = True):
    """device_put every param per its _sharding_spec (replicated if none or
    not divisible); buffers replicated."""
    for _, p in model.named_parameters():
        spec = filter_spec(p._sharding_spec if shard_specs else None,
                           mesh, p._value.ndim)
        if not _divisible(p._value.shape, spec, mesh):
            spec = P()
        p._update_value(jax.device_put(
            p._value, NamedSharding(mesh, spec)))
    for _, b in model.named_buffers():
        b._update_value(jax.device_put(
            b._value, NamedSharding(mesh, P())))
    return model


def shard_batch(mesh: Mesh, value, spec: P):
    v = value._value if isinstance(value, Tensor) else value
    spec = filter_spec(spec, mesh, getattr(v, "ndim", 0))
    if not _divisible(v.shape, spec, mesh):
        spec = P()
    out = jax.device_put(v, NamedSharding(mesh, spec))
    return Tensor(out) if isinstance(value, Tensor) else out


def replicate(mesh: Mesh, value):
    return shard_batch(mesh, value, P())
