"""Process spawn for multi-process tests/training (reference:
python/paddle/distributed/spawn.py — verify). On TPU a host usually runs
ONE process owning all local chips, so spawn is mainly for CPU-backend
multi-process tests (the reference's Gloo-on-CPU pattern, SURVEY §4)."""
from __future__ import annotations

import multiprocessing as mp
import os
import socket
from typing import Callable

__all__ = ["spawn", "find_free_port"]


def find_free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker(fn, rank, nprocs, port, args, backend):
    os.environ["JAX_PLATFORMS"] = backend
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    os.environ["PADDLE_MASTER"] = f"127.0.0.1:{port}"
    os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    os.environ["JAX_PROCESS_ID"] = str(rank)
    os.environ["JAX_NUM_PROCESSES"] = str(nprocs)
    fn(*args)


def spawn(func: Callable, args=(), nprocs=1, join=True, daemon=False,
          backend="cpu", **options):
    ctx = mp.get_context("spawn")
    port = find_free_port()
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_worker,
                        args=(func, rank, nprocs, port, args, backend),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        for p in procs:
            if p.exitcode != 0:
                raise RuntimeError(
                    f"spawned process exited with code {p.exitcode}")
    return procs
