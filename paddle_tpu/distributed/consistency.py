"""Cross-rank program-consistency checking.

Reference parity: the reference guards races/hangs with PADDLE_ENFORCE +
the stream-safe allocator, and its multi-rank hang class is NCCL ranks
executing mismatched collectives (SURVEY §5 "race detection").

TPU-native design: inside one XLA program races cannot happen — the
failure mode that remains is RANK DIVERGENCE: two processes jit
different programs (different flags/env/data shapes) and then hang in a
collective. This module turns that hang into a fast, actionable error:
every rank fingerprints its compiled program (StableHLO hash) and
cross-checks via the TCPStore before stepping.
"""
from __future__ import annotations

import hashlib
import os
import time
from typing import Optional

from ..utils.flags import env_int

__all__ = ["program_fingerprint", "check_program_consistency",
           "ConsistencyError"]


class ConsistencyError(RuntimeError):
    pass


def program_fingerprint(fn, *example_args, static_argnums=()) -> str:
    """SHA-256 of the lowered StableHLO of ``jax.jit(fn)`` on the example
    arguments — identical iff the ranks compiled the same program."""
    import jax

    jitted = fn if hasattr(fn, "lower") else jax.jit(
        fn, static_argnums=static_argnums)
    text = jitted.lower(*example_args).as_text()
    return hashlib.sha256(text.encode()).hexdigest()


def check_program_consistency(fingerprint: str, store=None,
                              rank: Optional[int] = None,
                              world_size: Optional[int] = None,
                              key: str = "consistency/program",
                              timeout: float = 60.0) -> bool:
    """Publish this rank's fingerprint and compare against all ranks.
    Raises ConsistencyError naming the diverging ranks instead of letting
    the job hang in a collective."""
    if rank is None:
        rank = env_int("PADDLE_TRAINER_ID", 0)
    if world_size is None:
        world_size = env_int("PADDLE_TRAINERS_NUM", 1)
    if world_size <= 1:
        return True
    if store is None:
        from ..core.native_api import TCPStore
        host, port = os.environ["PADDLE_MASTER"].rsplit(":", 1)
        store = TCPStore(host, int(port), world_size=world_size,
                         timeout=timeout)
    store.set(f"{key}/{rank}", fingerprint)
    mismatched = []
    deadline = time.monotonic() + timeout
    for r in range(world_size):
        # poll, don't block: TCPStore.get waits forever on a missing key,
        # which would turn "rank r never compiled" into exactly the hang
        # this check exists to prevent
        while not store.check(f"{key}/{r}"):
            if time.monotonic() > deadline:
                raise ConsistencyError(
                    f"rank {r} did not publish a program fingerprint "
                    f"within {timeout:.0f}s — it likely crashed before "
                    "compile or diverged in setup.")
            time.sleep(0.02)
        other = store.get(f"{key}/{r}").decode()
        if other != fingerprint:
            mismatched.append((r, other[:12]))
    if mismatched:
        raise ConsistencyError(
            f"rank {rank} compiled program {fingerprint[:12]} but "
            f"rank(s) {[r for r, _ in mismatched]} compiled "
            f"{[h for _, h in mismatched]} — the job would hang at the "
            "first collective. Check per-rank env/flags/data shapes.")
    return True
