"""paddle.distributed.utils — reference parity namespace
(python/paddle/distributed/utils/ — verify): the MoE expert-exchange
ops live here in the reference's public API, plus small env helpers."""
from __future__ import annotations

import os

from .communication import global_gather, global_scatter  # noqa: F401

__all__ = ["global_scatter", "global_gather", "get_host_name_ip"]


def get_host_name_ip():
    """(hostname, ip) of this node, or None on resolution failure
    (reference: paddle.distributed.utils.get_host_name_ip — verify)."""
    import socket
    try:
        host = socket.gethostname()
        return host, socket.gethostbyname(host)
    except OSError:
        return None
