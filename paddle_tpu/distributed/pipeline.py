"""Pipeline parallelism — single-program scan+ppermute schedule.

Reference parity: fleet's pipeline runtime
(python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py,
pp_utils/p2p_communication.py, and the C++ actor runtime in
paddle/fluid/distributed/fleet_executor/ — verify): micro-batch schedules
FThenB / 1F1B with NCCL p2p send/recv between stage processes.

TPU-native design (SURVEY §7 hard part #2): all stages live in ONE XLA
program.  Stage weights are stacked along a leading axis sharded over the
"pp" mesh axis; the microbatch loop is a ``lax.scan`` over T = M + S - 1
ticks inside ``shard_map`` (manual over "pp" only — dp/mp/sep stay "auto"
so GSPMD still lays out everything else).  Each tick every stage runs its
segment on its in-flight microbatch and hands the activation to the next
stage via ``ppermute`` — the TPU analogue of the reference's
batch_isend_irecv ring.  Differentiating through the scan yields the
reverse schedule automatically (backward ticks run newest-first, i.e. the
B phase of 1F1B); ``jax.checkpoint`` on the stage body gives the standard
per-microbatch activation-recompute memory profile.

The schedule is the *looped/circular* GPipe-with-steady-state form: bubble
fraction (S-1)/(M+S-1), identical to FThenB; because XLA overlaps the
ppermute with the next tick's compute (async collective + latency-hiding
scheduler), the steady state matches 1F1B's utilisation without the
hand-written interleave state machine.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["pipeline_spmd", "pipeline_spmd_interleaved",
           "split_microbatches", "merge_microbatches",
           "num_pipeline_stages", "PipelineParallel"]


def num_pipeline_stages(mesh: Optional[Mesh], axis: str = "pp") -> int:
    if mesh is None or axis not in mesh.axis_names:
        return 1
    return int(mesh.shape[axis])


def split_microbatches(x: jnp.ndarray, num_microbatches: int) -> jnp.ndarray:
    """(b, ...) -> (M, b/M, ...). M is clamped to the largest divisor of b
    that is <= num_microbatches (a silent clamp would hide nothing: the
    schedule is correct for any M; only the bubble fraction changes)."""
    b = x.shape[0]
    m = max(1, min(int(num_microbatches), b))
    while b % m != 0:
        m -= 1
    return x.reshape(m, b // m, *x.shape[1:])


def merge_microbatches(x_mb: jnp.ndarray) -> jnp.ndarray:
    return x_mb.reshape(x_mb.shape[0] * x_mb.shape[1], *x_mb.shape[2:])


def pipeline_spmd(stage_fn: Callable, stage_params: Any, x_mb: jnp.ndarray,
                  *, mesh: Mesh, axis: str = "pp",
                  mb_extras: Sequence[Any] = (),
                  extras: Sequence[Any] = (),
                  remat: bool = False) -> jnp.ndarray:
    """Run ``stage_fn`` as an S-stage pipeline over microbatches.

    stage_fn(params_local, x, *mb_extra_slices, *extras) -> y, with
    y.shape == x.shape (residual-stream discipline: every stage maps the
    hidden state to the hidden state, like the reference's PipelineLayer
    segments).

    stage_params: pytree whose leaves have leading dim S, sharded over
        ``axis`` (device d holds stage d's slice).
    x_mb: (M, mb, ...) microbatched input, replicated over ``axis``
        (other mesh axes are auto — dp sharding of mb flows through).
    mb_extras: pytrees with leading dim M, delivered per-microbatch to the
        *first* stage alongside x (e.g. a per-sample mask).
    extras: broadcast to every stage every tick (e.g. rope cos/sin).
    """
    S = num_pipeline_stages(mesh, axis)
    if S == 1:
        # no pp axis: one "stage" maps every microbatch in sequence
        local = jax.tree.map(lambda l: l[0], stage_params)
        fn0 = jax.checkpoint(stage_fn) if remat else stage_fn

        def body(_, sl):
            xs, mbx = sl
            return None, fn0(local, xs, *mbx, *extras)
        _, out = jax.lax.scan(body, None, (x_mb, tuple(mb_extras)))
        return out

    M = int(x_mb.shape[0])
    T = M + S - 1
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def inner(params_local, x_local, mbx_local, ex_local):
        # shard_map keeps the sharded stage dim at local size 1 — drop it
        params_local = jax.tree.map(lambda l: l[0], params_local)
        idx = jax.lax.axis_index(axis)

        def vary(v):
            return jax.lax.pcast(v, (axis,), to="varying")
        state = vary(jnp.zeros_like(x_local[0]))
        outputs = vary(jnp.zeros_like(x_local))
        # per-microbatch extras travel the ring WITH their activation:
        # stage i at tick t is processing microbatch t-i, so the extras
        # are injected at stage 0 and ppermuted alongside the state
        ex_state = jax.tree.map(lambda e: vary(jnp.zeros_like(e[0])),
                                mbx_local)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            state, ex_state, outputs = carry
            m_in = jnp.clip(t, 0, M - 1)
            inp = jax.lax.dynamic_index_in_dim(x_local, m_in, 0,
                                               keepdims=False)
            cur = jnp.where(idx == 0, inp, state)
            mbs = jax.tree.map(
                lambda e, cur_e: jnp.where(
                    idx == 0,
                    jax.lax.dynamic_index_in_dim(e, m_in, 0,
                                                 keepdims=False),
                    cur_e),
                mbx_local, ex_state)
            y = fn(params_local, cur, *mbs, *ex_local)
            m_out = jnp.clip(t - (S - 1), 0, M - 1)
            written = jax.lax.dynamic_update_index_in_dim(outputs, y,
                                                          m_out, 0)
            outputs = jnp.where(t >= S - 1, written, outputs)
            state = jax.lax.ppermute(y, axis, perm)
            ex_state = jax.tree.map(
                lambda e: jax.lax.ppermute(e, axis, perm), mbs)
            return (state, ex_state, outputs), None

        (state, ex_state, outputs), _ = jax.lax.scan(
            tick, (state, ex_state, outputs), jnp.arange(T))
        # results live on the last stage; psum broadcasts them everywhere
        # (XLA lowers the masked psum to a one-hot broadcast over pp)
        outputs = jnp.where(idx == S - 1, outputs, 0)
        return jax.lax.psum(outputs, axis)

    shmapped = jax.shard_map(
        inner, mesh=mesh, axis_names={axis},
        in_specs=(jax.tree.map(lambda _: P(axis), stage_params),
                  P(), jax.tree.map(lambda _: P(), tuple(mb_extras)),
                  jax.tree.map(lambda _: P(), tuple(extras))),
        out_specs=P())
    return shmapped(stage_params, x_mb, tuple(mb_extras), tuple(extras))


def pipeline_spmd_interleaved(stage_fn, stage_params, x_mb, *, mesh: Mesh,
                              axis: str = "pp", extras: Sequence[Any] = (),
                              remat: bool = False) -> jnp.ndarray:
    """Interleaved (VPP / circular) schedule (reference:
    PipelineParallelWithInterleave, meta_parallel/pipeline_parallel.py —
    verify): device d owns V model CHUNKS {d, S+d, ..., (V-1)S+d}; an
    activation makes V laps around the ppermute ring before exiting.

    Tick math: microbatch m enters at tick e_m = (m//S)·V·S + m%S, hops
    one device per tick for V·S ticks (chunk k//S at hop k), so total
    T = M·V + S - 1 ticks of ONE-chunk work — bubble (S-1)/(M·V+S-1),
    a factor V smaller than the non-interleaved (S-1)/(M+S-1) at equal
    microbatch count (Megatron VPP's trade: V× more p2p hops, each
    1/V the compute).

    stage_params: pytree with leading dims (S, V, ...) — device s holds
    [s, v] = global chunk v·S + s. stage_fn(chunk_params, x, *extras)
    must be shape-preserving. M must be a multiple of S (pad upstream).
    """
    S = num_pipeline_stages(mesh, axis)
    V = int(jax.tree.leaves(stage_params)[0].shape[1])
    M = int(x_mb.shape[0])
    if S == 1:
        local = jax.tree.map(lambda l: l[0], stage_params)  # (V, U, ...)
        fn0 = jax.checkpoint(stage_fn) if remat else stage_fn

        def per_mb(_, xs):
            def chunk_body(hh, chunk):
                return fn0(chunk, hh, *extras), None
            h, _ = jax.lax.scan(chunk_body, xs, local)
            return None, h
        _, out = jax.lax.scan(per_mb, None, x_mb)
        return out
    if M % S != 0:
        raise ValueError(
            f"interleaved schedule needs microbatches ({M}) divisible "
            f"by pp degree ({S}); pad the batch or change M")
    T = M * V + S - 1
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def inner(params_local, x_local, ex_local):
        params_local = jax.tree.map(lambda l: l[0], params_local)  # (V,…)
        idx = jax.lax.axis_index(axis)

        def vary(v):
            return jax.lax.pcast(v, (axis,), to="varying")
        state = vary(jnp.zeros_like(x_local[0]))
        outputs = vary(jnp.zeros_like(x_local))
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            state, outputs = carry
            r = jnp.mod(t - idx, S)
            j = t - r
            q = j // (V * S)
            k = jnp.mod(j, V * S)
            m = S * q + r
            alive = (j >= 0) & (m < M)
            m_c = jnp.clip(m, 0, M - 1)
            inp = jax.lax.dynamic_index_in_dim(x_local, m_c, 0,
                                               keepdims=False)
            cur = jnp.where(k == 0, inp, state)
            chunk = jax.tree.map(
                lambda l: jax.lax.dynamic_index_in_dim(
                    l, jnp.clip(k // S, 0, V - 1), 0, keepdims=False),
                params_local)
            y = fn(chunk, cur, *ex_local)
            y = jnp.where(alive, y, state)
            written = jax.lax.dynamic_update_index_in_dim(
                outputs, y, m_c, 0)
            outputs = jnp.where(alive & (k == V * S - 1), written,
                                outputs)
            state = jax.lax.ppermute(y, axis, perm)
            return (state, outputs), None

        (state, outputs), _ = jax.lax.scan(
            tick, (state, outputs), jnp.arange(T))
        # finished microbatches were written on the LAST device
        outputs = jnp.where(idx == S - 1, outputs, 0)
        return jax.lax.psum(outputs, axis)

    shmapped = jax.shard_map(
        inner, mesh=mesh, axis_names={axis},
        in_specs=(jax.tree.map(lambda _: P(axis), stage_params),
                  P(), jax.tree.map(lambda _: P(), tuple(extras))),
        out_specs=P())
    return shmapped(stage_params, x_mb, tuple(extras))


# ---------------------------------------------------------------------------
# Fleet API wrapper (reference: meta_parallel/pipeline_parallel.py — verify)
# ---------------------------------------------------------------------------

class PipelineParallel:
    """fleet.distributed_model's wrapper for PipelineLayer models.

    The reference runs an inter-process 1F1B state machine here; on TPU
    the schedule is compiled into the jitted train step (see
    ``pipeline_spmd``), so this wrapper only carries API parity: it owns
    the model + hcg and exposes ``forward_backward_pipeline`` /
    ``train_batch`` driving a fused TrainStep."""

    def __init__(self, layers, hcg=None, strategy=None, **kwargs):
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        self._step = None
        self._loss_fn = getattr(layers, "loss_fn", None)
        # strategy.pipeline_configs["accumulate_steps"] is the reference's
        # microbatch count; route it into the scan schedule
        if strategy is not None and \
                getattr(layers, "num_microbatches", None) is None:
            acc = int(getattr(strategy, "pipeline_configs", {})
                      .get("accumulate_steps", 1))
            if acc > 1:
                layers.num_microbatches = acc

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _require_loss_fn(self):
        if self._loss_fn is None:
            raise ValueError(
                "PipelineLayer was built without loss_fn; pipeline "
                "training needs it (PipelineLayer(..., loss_fn=...))")
        return self._loss_fn

    def _ensure_step(self, optimizer):
        if self._step is None or self._step.optimizer is not optimizer:
            from ..jit import TrainStep
            layer_loss = self._require_loss_fn()

            def loss_fn(model, batch):
                x, y = batch
                return layer_loss(model(x), y)
            self._step = TrainStep(self._layers, loss_fn, optimizer)
        return self._step

    def forward_backward_pipeline(self, data, scaler=None):
        x, y = data
        loss = self._require_loss_fn()(self._layers(x), y)
        loss.backward()
        return loss

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        step = self._ensure_step(optimizer)
        loss = step(tuple(data))
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss
