"""Group-sharded data parallelism (ZeRO stages 1/2/3).

Reference parity: ``paddle.distributed.sharding.group_sharded_parallel``
(reference: python/paddle/distributed/sharding/group_sharded.py — verify)
and the fleet stage wrappers (python/paddle/distributed/fleet/meta_parallel/
sharding/group_sharded_stage{2,3}.py, sharding_optimizer.py — verify).

TPU-native design: the reference implements each stage with hand-written
broadcast/reduce-scatter/allgather choreography over NCCL. On TPU all of
that is a *placement decision* handed to GSPMD:

- stage "os"      (ZeRO-1): optimizer slots are device_put sharded over the
  sharding axis at creation and kept sharded inside the jitted train step
  via with_sharding_constraint — XLA emits the reduce-scatter/allgather
  pair around the update automatically.
- stage "os_g"    (ZeRO-2): additionally the gradients are constrained to
  the same sharded placement before the update (reduce-scatter of grads).
- stage "p_g_os"  (ZeRO-3): additionally parameters themselves carry a
  sharded placement (allgather-on-use is native GSPMD behavior).

No bucketing/fusion machinery is needed: the XLA latency-hiding scheduler
overlaps the collectives with compute, which is what the reference's
comm-overlap options hand-build.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...nn.layer import Layer
from ...optimizer import Optimizer
from ..mesh import get_current_mesh

__all__ = ["group_sharded_parallel", "save_group_sharded_model",
           "ShardingConstrainer"]


def _pick_axis(group=None) -> str:
    """Prefer an explicit "sharding" mesh axis; else shard over "dp".
    `group` is accepted for reference-API compatibility but the axis choice
    is mesh-driven — a (Mesh, axis) pair IS the process group on TPU."""
    mesh = get_current_mesh()
    if mesh is None:
        return "sharding"
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    if sizes.get("sharding", 1) > 1:
        return "sharding"
    if sizes.get("dp", 1) > 1:
        return "dp"
    return "sharding"


def _sharded_spec(shape, axis: str, mesh: Mesh) -> Optional[P]:
    """Spec sharding the largest divisible dim over `axis`; None if no dim
    divides (stay replicated — the reference pads instead; we keep exact
    shapes so XLA never sees ragged tiles)."""
    if axis not in mesh.axis_names:
        return None
    n = int(np.prod([s for a, s in zip(mesh.axis_names, mesh.devices.shape)
                     if a == axis]))
    if n <= 1 or not shape:
        return None
    order = sorted(range(len(shape)), key=lambda i: -int(shape[i]))
    for i in order:
        if int(shape[i]) % n == 0 and int(shape[i]) >= n:
            spec = [None] * len(shape)
            spec[i] = axis
            return P(*spec)
    return None


class ShardingConstrainer:
    """Callable attached to the optimizer; maps (array, pname) -> array with
    the group-sharded placement applied (constraint inside jit, device_put
    outside)."""

    def __init__(self, axis: str):
        self.axis = axis

    def __call__(self, value, pname=None, slot=None):
        mesh = get_current_mesh()
        if mesh is None:
            return value
        if isinstance(value, jax.ShapeDtypeStruct):
            # abstract AOT scale check: attach the placement to the spec
            if len(value.shape) == 0:
                return value
            spec = _sharded_spec(value.shape, self.axis, mesh)
            if spec is None:
                return value
            return jax.ShapeDtypeStruct(
                value.shape, value.dtype,
                sharding=NamedSharding(mesh, spec))
        if not hasattr(value, "ndim") or value.ndim == 0:
            return value
        spec = _sharded_spec(value.shape, self.axis, mesh)
        if spec is None:
            return value
        sharding = NamedSharding(mesh, spec)
        # under tracing, device_put is NOT a sharding constraint — it
        # silently replicates; with_sharding_constraint is the in-program
        # placement op GSPMD honors
        if isinstance(value, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(value, sharding)
        return jax.device_put(value, sharding)


def group_sharded_parallel(model: Optional[Layer], optimizer: Optimizer,
                           level: str, scaler=None, group=None,
                           offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """Apply ZeRO-style group sharding. level ∈ {"os", "os_g", "p_g_os"}.

    Returns (model, optimizer, scaler) like the reference API. `model` may
    be None to attach only the optimizer-side hooks (fleet wires the model
    placement separately in distributed_model).
    """
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError(
            f"level must be one of os / os_g / p_g_os, got {level!r}")
    axis = _pick_axis(group)
    constrainer = ShardingConstrainer(axis)
    # stage >= 1: shard optimizer slots
    optimizer._slot_constrain = constrainer
    if level in ("os_g", "p_g_os"):
        optimizer._grad_constrain = constrainer
    # opt-in bucketed/quantized grad sync for shard_map-driven steps
    # (CollectiveConfig.bucketed_grad_sync, default off; a no-op under
    # plain GSPMD jit where the axis is unbound)
    from ..collectives import attach_grad_sync
    attach_grad_sync(optimizer, axes=(axis,))
    if level == "p_g_os" and model is not None:
        mesh = get_current_mesh()
        for _, p in model.named_parameters():
            if p.stop_gradient:
                continue
            if getattr(p, "_sharding_spec", None) is None and mesh is not None:
                spec = _sharded_spec(p._value.shape, axis, mesh)
                if spec is not None:
                    p._sharding_spec = spec
        if mesh is not None:
            from ..sharding_utils import place_model
            place_model(model, mesh)
    # re-place any already-created slots
    if optimizer._slots:
        for n, s in optimizer._slots.items():
            optimizer._slots[n] = {k: constrainer(v, n) for k, v in s.items()}
    return model, optimizer, scaler


def save_group_sharded_model(model: Layer, output: str, optimizer=None):
    """Reference: save_group_sharded_model gathers stage-3 params first; on
    TPU jax arrays are addressable globally, so a plain state_dict works."""
    import os
    from ...serialization import save
    os.makedirs(output, exist_ok=True)
    save(model.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
