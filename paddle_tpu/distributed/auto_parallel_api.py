"""Semi-automatic parallelism: shard_tensor / placements / reshard.

Reference parity: paddle.distributed.{ProcessMesh, shard_tensor, Shard,
Replicate, Partial, reshard} + the SPMD-rule/reshard machinery
(python/paddle/distributed/auto_parallel/, paddle/phi/core/distributed/
auto_parallel/ — verify).

TPU-native design (SURVEY §7): placements map 1:1 onto
``jax.sharding.NamedSharding`` partition specs; *SPMD rules and reshard are
GSPMD* — annotating inputs/outputs is enough, XLA propagates shardings
through every op and inserts the collectives the reference implements by
hand (s→r all_gather, r→s slice, p→r all_reduce, cross-mesh all-to-all)."""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..tensor import Tensor, Parameter

__all__ = ["ProcessMesh", "Placement", "Shard", "Replicate", "Partial",
           "shard_tensor", "dtensor_from_fn", "reshard", "shard_layer",
           "shard_optimizer", "to_static", "DistAttr", "Engine",
           "DistModel"]


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicate(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    def __init__(self, dim):
        self.dim = dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, o):
        return isinstance(o, Shard) and o.dim == self.dim

    def __hash__(self):
        return hash(("S", self.dim))


class Replicate(Placement):
    def is_replicate(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, o):
        return isinstance(o, Replicate)

    def __hash__(self):
        return hash("R")


class Partial(Placement):
    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type})"


class ProcessMesh:
    """N-d logical process mesh (reference: paddle.distributed.ProcessMesh).
    Backed by a jax Mesh over the same device array."""

    def __init__(self, mesh=None, dim_names=None, shape=None,
                 process_ids=None):
        if mesh is not None:
            arr = np.asarray(mesh)
        else:
            arr = np.asarray(process_ids).reshape(shape)
        self._ids = arr
        self.dim_names = list(dim_names) if dim_names else [
            f"d{i}" for i in range(arr.ndim)]
        devices = np.asarray(jax.devices(), dtype=object)
        if arr.size > len(devices):
            raise ValueError(
                f"ProcessMesh wants {arr.size} devices, have {len(devices)}")
        dev_arr = np.empty(arr.shape, dtype=object)
        flat_ids = arr.reshape(-1)
        for i, did in enumerate(flat_ids):
            dev_arr.reshape(-1)[i] = devices[int(did)]
        self.jax_mesh = Mesh(dev_arr, tuple(self.dim_names))

    @property
    def shape(self):
        return list(self._ids.shape)

    @property
    def ndim(self):
        return self._ids.ndim

    @property
    def process_ids(self):
        return self._ids.reshape(-1).tolist()

    def get_dim_size(self, name):
        return self._ids.shape[self.dim_names.index(name)]

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, " \
               f"dim_names={self.dim_names})"


class DistAttr:
    """Tensor dist attr: (mesh, placements) (reference: TensorDistAttr
    process_mesh+dims_mapping — verify)."""

    def __init__(self, mesh: ProcessMesh, placements):
        self.process_mesh = mesh
        self.placements = list(placements)

    def __repr__(self):
        return f"DistAttr(mesh={self.process_mesh}, " \
               f"placements={self.placements})"


def _to_partition_spec(mesh: ProcessMesh, placements, ndim: int,
                       allow_partial: bool = False):
    """placements: one Placement per MESH dim (paddle convention) →
    PartitionSpec over TENSOR dims. Partial placements are handled by
    the caller (stacked contribution dims); reaching one here without
    ``allow_partial`` is an error, never a silent drop."""
    spec = [None] * ndim
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            axis_name = mesh.dim_names[mesh_dim]
            if spec[p.dim] is None:
                spec[p.dim] = axis_name
            elif isinstance(spec[p.dim], tuple):
                spec[p.dim] = spec[p.dim] + (axis_name,)
            else:
                spec[p.dim] = (spec[p.dim], axis_name)
        elif isinstance(p, Partial) and not allow_partial:
            raise ValueError(
                "Partial placement must go through shard_tensor/reshard "
                "(stacked contribution representation); it cannot be "
                "expressed as a plain PartitionSpec.")
    return PartitionSpec(*spec)


def _partial_mesh_dims(placements):
    return [i for i, p in enumerate(placements) if isinstance(p, Partial)]


def _place_with_partial(value, mesh: ProcessMesh, placements):
    """Build the on-device representation for ``placements`` from a
    DENSE value.

    Partial(axis) is represented as an explicit leading contribution dim
    of size mesh[axis], sharded over that axis (TPU-native 'unreduced'
    state: the global value is the sum over the dim — summing it lowers
    to a psum over the axis, exactly the reference's p→r AllReduce
    reshard). For a fresh partial tensor, slot 0 carries the full value
    and the rest are zero, matching TensorDistAttr partial init."""
    pdims = _partial_mesh_dims(placements)
    base_spec = _to_partition_spec(mesh, placements, value.ndim,
                                   allow_partial=True)
    if not pdims:
        return jax.device_put(value, NamedSharding(mesh.jax_mesh,
                                                   base_spec)), []
    axis_names = [mesh.dim_names[d] for d in pdims]
    import jax.numpy as jnp
    for d in reversed(pdims):
        k = mesh.shape[d]
        pad = jnp.zeros((k - 1,) + value.shape, value.dtype)
        value = jnp.concatenate([value[None], pad], axis=0)
    spec = PartitionSpec(*axis_names, *tuple(base_spec))
    return jax.device_put(value, NamedSharding(mesh.jax_mesh, spec)), \
        axis_names


def shard_tensor(x, mesh: ProcessMesh, placements, dtype=None,
                 stop_gradient=None):
    """Places `x` on the mesh with the given placements; ops consume it and
    GSPMD propagates (reference: dist.shard_tensor creating DistTensor).
    Partial placements produce an unreduced tensor resolved (psum) on
    first consumption — see tensor._departial."""
    t = x if isinstance(x, Tensor) else Tensor(jax.numpy.asarray(x))
    pdims = _partial_mesh_dims(placements)
    if pdims and isinstance(t, Parameter):
        raise ValueError("Partial placement on a Parameter is not "
                         "supported (parameters are dense state)")
    # a Partial source contributes its DENSE (summed) value
    v, partial_axes = _place_with_partial(t._dense_value(), mesh,
                                          placements)
    if isinstance(t, Parameter):
        t._update_value(v)
        out = t
        out._sharding_spec = _to_partition_spec(mesh, placements,
                                                t._value.ndim)
    else:
        out = Tensor(v, stop_gradient=t.stop_gradient
                     if stop_gradient is None else stop_gradient)
        if partial_axes:
            out._partial_axes = partial_axes
    out.dist_attr = DistAttr(mesh, placements)
    out.process_mesh = mesh
    out.placements = list(placements)
    return out


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(x, mesh: ProcessMesh, placements):
    """Move a dist tensor to new placements — the s↔r reshard family of
    the reference collapses to one device_put (XLA figures out
    all_gather / slice / all-to-all). Partial transitions:

    - p → r/s: sum the contribution dims (psum over the partial axes;
      p→s additionally reshards, i.e. reduce-scatter under jit)
    - r/s → p: slot 0 of the new contribution dim carries the value,
      the rest are zero (reference TensorDistAttr partial init)
    """
    v, partial_axes = _place_with_partial(x._dense_value(), mesh,
                                          placements)
    out = Tensor(v, stop_gradient=x.stop_gradient)
    if partial_axes:
        out._partial_axes = partial_axes
    out.dist_attr = DistAttr(mesh, placements)
    out.process_mesh = mesh
    out.placements = list(placements)
    return out


def shard_layer(layer, process_mesh, shard_fn=None, input_fn=None,
                output_fn=None):
    """Apply a sharding plan to every sublayer's params (reference:
    dist.shard_layer)."""
    if shard_fn is None:
        def shard_fn(name, sublayer, mesh):
            for pname, p in sublayer._parameters.items():
                if p is not None:
                    shard_tensor(p, mesh,
                                 [Replicate()] * len(mesh.shape))
    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, process_mesh)
    return layer


def shard_optimizer(optimizer, shard_fn=None):
    """Re-place optimizer accumulator slots (reference:
    dist.shard_optimizer / _ShardOptimizer — verify).

    Default: every slot adopts its parameter's placements (so a Shard(0)
    param gets Shard(0) moments — the semi-auto analogue of sharded
    optimizer states). A custom ``shard_fn(accumulator_name, param)``
    (reference signature: accumulator name like "m"/"v"/"master", then
    the Parameter) may return a list of Placements (requires the param
    to carry dist_attr) or ``None`` to keep the default.

    Works through the optimizer's ``_slot_constrain`` hook so slots
    created lazily inside a jitted TrainStep are placed identically."""
    params = {n: p for n, p in zip(optimizer._param_names,
                                   optimizer._param_list)}

    def _constrain(slot_value, pname, slot_name=None):
        p = params.get(pname)
        if p is None:
            return slot_value
        if isinstance(slot_value, jax.ShapeDtypeStruct):
            # abstract AOT scale check: carry placement on the spec
            # (custom shard_fn placements included — the per-device
            # memory estimate must reflect them)
            if shard_fn is not None:
                placements = shard_fn(slot_name, p)
                if placements is not None:
                    mesh = getattr(p, "process_mesh", None)
                    if mesh is None:
                        # same contract as the concrete path: a dry-run
                        # must not validate a config that cannot run
                        raise ValueError(
                            f"shard_fn returned placements for '{pname}'"
                            " but the param has no process_mesh (use "
                            "dist.shard_tensor on it first)")
                    if len(slot_value.shape) > 0:
                        spec = _to_partition_spec(mesh, placements,
                                                  len(slot_value.shape))
                        return jax.ShapeDtypeStruct(
                            slot_value.shape, slot_value.dtype,
                            sharding=NamedSharding(mesh.jax_mesh, spec))
            psh = getattr(p._value, "sharding", None)
            if psh is not None and slot_value.shape == p._value.shape:
                return jax.ShapeDtypeStruct(slot_value.shape,
                                            slot_value.dtype,
                                            sharding=psh)
            return slot_value
        placements = None
        if shard_fn is not None:
            placements = shard_fn(slot_name, p)
        if placements is not None:
            mesh = getattr(p, "process_mesh", None)
            if mesh is None:
                raise ValueError(
                    f"shard_fn returned placements for '{pname}' but the "
                    "param has no process_mesh (use dist.shard_tensor on "
                    "it first)")
            if slot_value.ndim == 0:   # beta powers etc. stay replicated
                return slot_value
            spec = _to_partition_spec(mesh, placements, slot_value.ndim)
            return jax.lax.with_sharding_constraint(
                slot_value, NamedSharding(mesh.jax_mesh, spec)) \
                if _is_traced(slot_value) else jax.device_put(
                    slot_value, NamedSharding(mesh.jax_mesh, spec))
        # default: adopt the param's sharding
        sharding = getattr(p._value, "sharding", None)
        if sharding is None or slot_value.shape != p._value.shape:
            return slot_value
        return jax.lax.with_sharding_constraint(slot_value, sharding) \
            if _is_traced(slot_value) else jax.device_put(slot_value,
                                                          sharding)

    optimizer._slot_constrain = _constrain
    # re-place any slots that already exist
    for pname, slots in optimizer._slots.items():
        optimizer._slots[pname] = {k: _constrain(v, pname, k)
                                   for k, v in slots.items()}
    return optimizer


def _is_traced(v):
    import jax.core
    return isinstance(v, jax.core.Tracer)


class Engine:
    """Static auto-parallel engine (reference:
    python/paddle/distributed/auto_parallel/static/engine.py — verify:
    Engine.prepare → completion/partition/reshard pass pipeline;
    Engine.fit/evaluate/predict drive the partitioned program).

    TPU-native: `prepare` AOT-lowers ONE jitted SPMD train step (GSPMD is
    the completion+partitioner+reshard pipeline); fit/evaluate/predict
    drive it. ``cost()`` surfaces the compiled cost model the reference
    exposes through its cost estimator."""

    def __init__(self, model, loss=None, optimizer=None, metrics=None,
                 strategy=None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics or []
        self._strategy = strategy
        self._step = None
        self._compiled = None
        self.history = {"loss": []}

    def _loss_fn(self, model, batch):
        x, y = batch
        out = model(x)
        return self._loss(out, y)

    def _ensure_step(self):
        if self._step is None:
            from ..jit import TrainStep
            if self._loss is None or self._optimizer is None:
                raise ValueError("Engine.fit needs loss and optimizer")
            self._step = TrainStep(self._model, self._loss_fn,
                                   self._optimizer)
        return self._step

    def prepare(self, inputs_spec=None, labels_spec=None, mode="train"):
        """Build the jitted SPMD step; with specs (jax.ShapeDtypeStruct
        or example Tensors) also AOT-compile it so `cost()` is
        available. Returns self."""
        step = self._ensure_step()
        if inputs_spec is not None:
            if labels_spec is None:
                raise ValueError(
                    "prepare(inputs_spec, labels_spec): labels_spec is "
                    "required when inputs_spec is given (the step takes "
                    "an (inputs, labels) batch)")
            self._compiled = step.lower((inputs_spec, labels_spec)) \
                .compile()
        return self

    def cost(self):
        if self._compiled is None:
            raise ValueError("call prepare(inputs_spec, labels_spec) first")
        ca = self._compiled.cost_analysis()
        ma = self._compiled.memory_analysis()
        return {"flops": ca.get("flops", 0.0),
                "bytes_accessed": ca.get("bytes accessed", 0.0),
                "peak_temp_bytes": ma.temp_size_in_bytes,
                "argument_bytes": ma.argument_size_in_bytes}

    def dataloader(self, dataset, batch_size=32, shuffle=False,
                   mode="train"):
        from ..io import DataLoader, DistributedBatchSampler
        sampler = DistributedBatchSampler(dataset, batch_size=batch_size,
                                          shuffle=shuffle)
        return DataLoader(dataset, batch_sampler=sampler)

    def _resolve_loader(self, data, batch_size):
        """Dataset → wrap in a distributed loader; anything else
        (DataLoader, generator, list of pre-built batches) is iterated
        as-is."""
        from ..io import Dataset
        if isinstance(data, Dataset):
            return self.dataloader(data, batch_size=batch_size)
        return data

    def fit(self, train_data, epochs=1, batch_size=32, verbose=0,
            log_freq=50):
        step = self._ensure_step()
        loader = self._resolve_loader(train_data, batch_size)
        import jax
        for epoch in range(epochs):
            # losses stay on device inside the epoch: a per-step
            # float(loss.item()) forces a device→host sync each step and
            # defeats XLA async dispatch (reference logs on log_freq)
            pend = []
            try:
                for it, batch in enumerate(loader):
                    loss = step(tuple(batch))
                    pend.append(loss._value)
                    if verbose and it % log_freq == 0:
                        print(f"epoch {epoch} step {it}: "
                              f"loss {float(pend[-1]):.4f}")
            finally:
                # a mid-epoch crash/interrupt must not lose the completed
                # steps' losses from history
                self.history["loss"].extend(
                    float(v) for v in jax.device_get(pend))
        return self.history

    def evaluate(self, eval_data, batch_size=32):
        # losses stay on device inside the loop and are fetched once at
        # the end — a per-batch float(...item()) would sync the host
        # every step and defeat XLA async dispatch (VERDICT r3 weak #2;
        # fit() got this fix in r3, evaluate kept the defect)
        losses = []
        loader = self._resolve_loader(eval_data, batch_size)
        from .. import framework
        with framework.no_grad_guard():
            for batch in loader:
                x, y = batch
                losses.append(self._loss(self._model(x), y)._value)
        import jax
        vals = [float(v) for v in jax.device_get(losses)]
        return {"loss": sum(vals) / max(len(vals), 1)}

    def predict(self, test_data, batch_size=32):
        outs = []
        loader = self._resolve_loader(test_data, batch_size)
        from .. import framework
        with framework.no_grad_guard():
            for batch in loader:
                x = batch[0] if isinstance(batch, (tuple, list)) else batch
                outs.append(self._model(x))
        return outs

    def state_dict(self):
        return self._model.state_dict()

    def save(self, path):
        from .. import save
        save(self._model.state_dict(), path)

    def load(self, path):
        from .. import load
        self._model.set_state_dict(load(path))


class DistModel:
    """dist.to_static return type (reference: DistModel — verify): call
    it with a batch to run one optimized step in train mode, or a
    forward in eval/predict mode."""

    def __init__(self, engine: Engine):
        self._engine = engine
        self._mode = "train"

    def train(self):
        self._mode = "train"

    def eval(self):
        self._mode = "eval"

    def predict(self):
        self._mode = "predict"

    @property
    def mode(self):
        return self._mode

    def state_dict(self):
        return self._engine.state_dict()

    def __call__(self, *batch):
        if len(batch) == 1 and isinstance(batch[0], (tuple, list)):
            batch = tuple(batch[0])
        if self._mode == "train":
            return self._engine._ensure_step()(tuple(batch))
        from .. import framework
        model = self._engine._model
        with framework.no_grad_guard():
            if self._mode == "eval":
                x, y = batch
                return self._engine._loss(model(x), y)
            return model(batch[0])


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """dist.to_static: wrap a (sharded) layer into a DistModel driven by
    the static auto-parallel Engine (one jitted SPMD step; GSPMD plays
    the reference's completion→partition→reshard pass pipeline)."""
    engine = Engine(layer, loss=loss, optimizer=optimizer,
                    strategy=strategy)
    return DistModel(engine)
