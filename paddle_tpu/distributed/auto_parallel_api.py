"""Semi-automatic parallelism: shard_tensor / placements / reshard.

Reference parity: paddle.distributed.{ProcessMesh, shard_tensor, Shard,
Replicate, Partial, reshard} + the SPMD-rule/reshard machinery
(python/paddle/distributed/auto_parallel/, paddle/phi/core/distributed/
auto_parallel/ — verify).

TPU-native design (SURVEY §7): placements map 1:1 onto
``jax.sharding.NamedSharding`` partition specs; *SPMD rules and reshard are
GSPMD* — annotating inputs/outputs is enough, XLA propagates shardings
through every op and inserts the collectives the reference implements by
hand (s→r all_gather, r→s slice, p→r all_reduce, cross-mesh all-to-all)."""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..tensor import Tensor, Parameter

__all__ = ["ProcessMesh", "Placement", "Shard", "Replicate", "Partial",
           "shard_tensor", "dtensor_from_fn", "reshard", "shard_layer",
           "shard_optimizer", "to_static", "DistAttr"]


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicate(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    def __init__(self, dim):
        self.dim = dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, o):
        return isinstance(o, Shard) and o.dim == self.dim

    def __hash__(self):
        return hash(("S", self.dim))


class Replicate(Placement):
    def is_replicate(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, o):
        return isinstance(o, Replicate)

    def __hash__(self):
        return hash("R")


class Partial(Placement):
    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type})"


class ProcessMesh:
    """N-d logical process mesh (reference: paddle.distributed.ProcessMesh).
    Backed by a jax Mesh over the same device array."""

    def __init__(self, mesh=None, dim_names=None, shape=None,
                 process_ids=None):
        if mesh is not None:
            arr = np.asarray(mesh)
        else:
            arr = np.asarray(process_ids).reshape(shape)
        self._ids = arr
        self.dim_names = list(dim_names) if dim_names else [
            f"d{i}" for i in range(arr.ndim)]
        devices = np.asarray(jax.devices(), dtype=object)
        if arr.size > len(devices):
            raise ValueError(
                f"ProcessMesh wants {arr.size} devices, have {len(devices)}")
        dev_arr = np.empty(arr.shape, dtype=object)
        flat_ids = arr.reshape(-1)
        for i, did in enumerate(flat_ids):
            dev_arr.reshape(-1)[i] = devices[int(did)]
        self.jax_mesh = Mesh(dev_arr, tuple(self.dim_names))

    @property
    def shape(self):
        return list(self._ids.shape)

    @property
    def ndim(self):
        return self._ids.ndim

    @property
    def process_ids(self):
        return self._ids.reshape(-1).tolist()

    def get_dim_size(self, name):
        return self._ids.shape[self.dim_names.index(name)]

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, " \
               f"dim_names={self.dim_names})"


class DistAttr:
    """Tensor dist attr: (mesh, placements) (reference: TensorDistAttr
    process_mesh+dims_mapping — verify)."""

    def __init__(self, mesh: ProcessMesh, placements):
        self.process_mesh = mesh
        self.placements = list(placements)

    def __repr__(self):
        return f"DistAttr(mesh={self.process_mesh}, " \
               f"placements={self.placements})"


def _to_partition_spec(mesh: ProcessMesh, placements, ndim: int):
    """placements: one Placement per MESH dim (paddle convention) →
    PartitionSpec over TENSOR dims."""
    spec = [None] * ndim
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            axis_name = mesh.dim_names[mesh_dim]
            if spec[p.dim] is None:
                spec[p.dim] = axis_name
            elif isinstance(spec[p.dim], tuple):
                spec[p.dim] = spec[p.dim] + (axis_name,)
            else:
                spec[p.dim] = (spec[p.dim], axis_name)
    return PartitionSpec(*spec)


def shard_tensor(x, mesh: ProcessMesh, placements, dtype=None,
                 stop_gradient=None):
    """Places `x` on the mesh with the given placements; ops consume it and
    GSPMD propagates (reference: dist.shard_tensor creating DistTensor)."""
    t = x if isinstance(x, Tensor) else Tensor(jax.numpy.asarray(x))
    spec = _to_partition_spec(mesh, placements, t._value.ndim)
    sharding = NamedSharding(mesh.jax_mesh, spec)
    v = jax.device_put(t._value, sharding)
    if isinstance(t, Parameter):
        t._update_value(v)
        out = t
    else:
        out = Tensor(v, stop_gradient=t.stop_gradient
                     if stop_gradient is None else stop_gradient)
    if isinstance(out, Parameter):
        out._sharding_spec = spec
    out.dist_attr = DistAttr(mesh, placements)
    out.process_mesh = mesh
    out.placements = list(placements)
    return out


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(x, mesh: ProcessMesh, placements):
    """Move a dist tensor to new placements — the whole reshard function
    family of the reference collapses to one device_put (XLA figures out
    all_gather / slice / all-to-all)."""
    spec = _to_partition_spec(mesh, placements, x._value.ndim)
    v = jax.device_put(x._value, NamedSharding(mesh.jax_mesh, spec))
    out = Tensor(v, stop_gradient=x.stop_gradient)
    out.dist_attr = DistAttr(mesh, placements)
    out.process_mesh = mesh
    out.placements = list(placements)
    return out


def shard_layer(layer, process_mesh, shard_fn=None, input_fn=None,
                output_fn=None):
    """Apply a sharding plan to every sublayer's params (reference:
    dist.shard_layer)."""
    if shard_fn is None:
        def shard_fn(name, sublayer, mesh):
            for pname, p in sublayer._parameters.items():
                if p is not None:
                    shard_tensor(p, mesh,
                                 [Replicate()] * len(mesh.shape))
    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, process_mesh)
    return layer


def shard_optimizer(optimizer, shard_fn=None):
    """ZeRO-style optimizer-state sharding: slots inherit parameter
    shardings automatically (they are created zeros_like on the sharded
    param); a custom shard_fn can re-place them."""
    return optimizer


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """dist.to_static: returns a DistModel-like compiled trainer (the
    static auto-parallel Engine path). First-cut: TrainStep with sharded
    params already placed by shard_tensor/shard_layer."""
    from ..jit import TrainStep

    def loss_fn(model, batch):
        x, y = batch
        out = model(x)
        return loss(out, y)
    return TrainStep(layer, loss_fn, optimizer)
