"""Distributed launcher (``python -m paddle_tpu.distributed.launch``).

Reference parity: python/paddle/distributed/launch/ (Context arg/env
parsing, CollectiveController process watch, Pod/Container spawn, elastic
relaunch — verify).

TPU-native design: one worker process per HOST (a TPU host owns all its
local chips through one PJRT client, unlike the reference's
process-per-GPU), so ``--nproc_per_node`` defaults to 1; multi-host runs
rendezvous through the C++ TCPStore at ``--master`` and jax's
coordination service gets the same address. Failure handling is
relaunch-from-checkpoint: the watch loop restarts the whole local pod on
worker death (paddle's elastic manager semantics, SURVEY §5)."""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

from ..launch_utils import find_free_port
from ...utils.flags import env_int

__all__ = ["LaunchConfig", "launch_pod", "main"]


class LaunchConfig:
    def __init__(self, script: str, script_args=(), nnodes: int = 1,
                 node_rank: int = 0, nproc_per_node: int = 1,
                 master: Optional[str] = None, log_dir: str = "log",
                 max_restarts: int = 0, backend: Optional[str] = None,
                 envs: Optional[dict] = None):
        self.script = script
        self.script_args = list(script_args)
        self.nnodes = nnodes
        self.node_rank = node_rank
        self.nproc_per_node = nproc_per_node
        self.master = master or f"127.0.0.1:{find_free_port()}"
        self.log_dir = log_dir
        self.max_restarts = max_restarts
        self.backend = backend
        self.envs = envs or {}

    @property
    def world_size(self):
        return self.nnodes * self.nproc_per_node


def _worker_env(cfg: LaunchConfig, local_rank: int, restart: int) -> dict:
    rank = cfg.node_rank * cfg.nproc_per_node + local_rank
    env = dict(os.environ)
    env.update(cfg.envs)
    env.update({
        # the reference's env contract (SURVEY §2.4)
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(cfg.world_size),
        "PADDLE_MASTER": cfg.master,
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_RESTART_COUNT": str(restart),
        # jax distributed coordination mirrors it
        "JAX_COORDINATOR_ADDRESS": cfg.master,
        "JAX_PROCESS_ID": str(rank),
        "JAX_NUM_PROCESSES": str(cfg.world_size),
    })
    if cfg.backend:
        env["JAX_PLATFORMS"] = cfg.backend
        if cfg.backend == "cpu":
            # CPU workers must not dial the host's TPU plugin/tunnel at
            # interpreter startup (site hooks key off these vars); doing
            # so serializes every spawn behind an exclusive-chip claim.
            for var in ("PALLAS_AXON_POOL_IPS", "TPU_NAME",
                        "TPU_WORKER_HOSTNAMES"):
                env.pop(var, None)
    return env


def _spawn_pod(cfg: LaunchConfig, restart: int) -> List[subprocess.Popen]:
    os.makedirs(cfg.log_dir, exist_ok=True)
    procs = []
    for lr in range(cfg.nproc_per_node):
        rank = cfg.node_rank * cfg.nproc_per_node + lr
        log = open(os.path.join(cfg.log_dir,
                                f"workerlog.{rank}.r{restart}"), "w")
        cmd = [sys.executable, "-u", cfg.script] + cfg.script_args
        p = subprocess.Popen(cmd, env=_worker_env(cfg, lr, restart),
                             stdout=log, stderr=subprocess.STDOUT)
        p._pt_log = log  # keep handle for close
        procs.append(p)
    return procs


def _kill_pod(procs):
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.time() + 10
    for p in procs:
        try:
            p.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()
    for p in procs:
        p._pt_log.close()


def launch_pod(cfg: LaunchConfig) -> int:
    """Spawn the local pod and watch it. On a worker failure: if restarts
    remain, kill the pod and relaunch it (workers resume from their last
    checkpoint — the reference's elastic recovery model); else tear down
    and return the failing exit code."""
    restart = 0
    while True:
        procs = _spawn_pod(cfg, restart)
        failed_code = None
        while True:
            alive = 0
            for p in procs:
                code = p.poll()
                if code is None:
                    alive += 1
                elif code != 0 and failed_code is None:
                    failed_code = code
            if failed_code is not None or alive == 0:
                break
            time.sleep(0.2)
        if failed_code is None:
            for p in procs:
                p._pt_log.close()
            return 0
        _kill_pod(procs)
        if restart >= cfg.max_restarts:
            print(f"[launch] worker failed with exit code {failed_code}; "
                  f"no restarts left", file=sys.stderr)
            return failed_code
        restart += 1
        print(f"[launch] worker failed (exit {failed_code}); relaunching "
              f"pod (restart {restart}/{cfg.max_restarts})",
              file=sys.stderr)


def main(argv=None):
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="launch distributed training "
                    "(one worker process per TPU host)")
    parser.add_argument("--nnodes", type=int, default=1)
    parser.add_argument("--node_rank", type=int,
                        default=env_int("PADDLE_NODE_RANK", 0))
    parser.add_argument("--nproc_per_node", type=int, default=1)
    parser.add_argument("--master", type=str, default=None,
                        help="host:port of the rank-0 rendezvous store")
    parser.add_argument("--log_dir", type=str, default="log")
    parser.add_argument("--max_restarts", type=int, default=0,
                        help=">0 enables elastic relaunch-on-failure")
    parser.add_argument("--backend", type=str, default=None,
                        help="override JAX_PLATFORMS for workers")
    parser.add_argument("--devices", type=str, default=None,
                        help="accepted for reference-CLI compatibility; "
                        "TPU visibility is per-host, so this is ignored")
    parser.add_argument("script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    cfg = LaunchConfig(
        script=args.script, script_args=args.script_args,
        nnodes=args.nnodes, node_rank=args.node_rank,
        nproc_per_node=args.nproc_per_node, master=args.master,
        log_dir=args.log_dir, max_restarts=args.max_restarts,
        backend=args.backend)
    return launch_pod(cfg)
