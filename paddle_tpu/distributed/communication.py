"""Eager collective + point-to-point communication API.

Reference parity: python/paddle/distributed/communication/ +
paddle/phi/core/distributed/ProcessGroup* (NCCL) — verify.

TPU-native design: the *perf path* never calls these eagerly — GSPMD emits
collectives inside jitted programs over the mesh (SURVEY §2.4). This module
provides the paddle-compatible eager API for host-level coordination:

- world-scoped collectives lower to jax multihost utilities (tiny XLA
  collective programs over DCN/ICI);
- subset ``Group`` collectives and all point-to-point ops (send/recv/
  isend/irecv/batch_isend_irecv) ride the C++ TCPStore key-value rendezvous
  (``paddle_tpu.core.native_api.TCPStore``) — the same transport the
  reference's gloo/TCPStore host path uses. They are host-bandwidth
  control-plane ops by design; bulk tensor exchange belongs inside jitted
  programs (shard_map ppermute / collective_permute).

Eager ``reduce_scatter``/``alltoall`` across processes are implemented via
allgather-then-slice: O(world) traffic, correctness-only — documented,
deliberate (the O(shard) path is the GSPMD one inside jit).
"""
from __future__ import annotations

import dataclasses
import io
import os
import pickle
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor
from ..utils.flags import env_float, env_int, env_str

__all__ = ["ReduceOp", "Group", "all_reduce", "all_gather",
           "all_gather_object", "reduce_scatter", "broadcast", "scatter",
           "reduce", "alltoall", "alltoall_single", "global_scatter",
           "global_gather", "send", "recv",
           "barrier", "new_group", "get_group", "destroy_process_group",
           "wait", "stream", "P2POp", "batch_isend_irecv", "isend", "irecv"]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    def __init__(self, ranks, gid=0, name=None):
        self.ranks = list(ranks)
        self.id = gid
        self.name = name or f"group_{gid}"

    @property
    def nranks(self):
        return len(self.ranks)

    @property
    def world_size(self):
        return len(self.ranks)

    @property
    def rank(self):
        pid = _my_rank()
        return self.ranks.index(pid) if pid in self.ranks else -1

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def is_member(self):
        return _my_rank() in self.ranks

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks})"


_GROUPS: dict[int, Group] = {}
_NEXT_GID = [1]


def _my_rank() -> int:
    return env_int("PADDLE_TRAINER_ID", jax.process_index())


def _world_size() -> int:
    return env_int("PADDLE_TRAINERS_NUM", jax.process_count())


def _world():
    if 0 not in _GROUPS:
        _GROUPS[0] = Group(list(range(_world_size())), 0, "world")
    return _GROUPS[0]


def new_group(ranks=None, backend=None, timeout=None):
    """Create a communication group over ``ranks``.

    Group ids are assigned from a process-local monotonically increasing
    counter; as in the reference, every rank must call ``new_group`` in the
    same order so ids agree across the job."""
    gid = _NEXT_GID[0]
    _NEXT_GID[0] += 1
    g = Group(sorted(ranks) if ranks is not None
              else list(range(_world_size())), gid)
    _GROUPS[gid] = g
    return g


def get_group(gid=0):
    return _GROUPS.get(gid, _world())


def destroy_process_group(group=None):
    global _STORE
    if group is not None and group.id in _GROUPS and group.id != 0:
        del _GROUPS[group.id]
        return
    _GROUPS.clear()
    with _STORE_LOCK:
        if _STORE is not None and hasattr(_STORE, "close"):
            try:
                _STORE.close()
            except Exception:
                pass
        _STORE = None
    # reset sequence counters so a re-initialized job starts in lock-step
    # with fresh peers (elastic restart path)
    with _SEQ_LOCK:
        _SEND_SEQ.clear()
        _RECV_SEQ.clear()
        _COLL_SEQ.clear()
    _NEXT_GID[0] = 1


def _val(t):
    return t._value if isinstance(t, Tensor) else jnp.asarray(t)


def _single_process() -> bool:
    return _world_size() == 1


def _is_world(group) -> bool:
    return group is None or group.id == 0 or \
        sorted(group.ranks) == list(range(_world_size()))


# --------------------------------------------------------------------------
# store transport (p2p + subset-group collectives)
# --------------------------------------------------------------------------

class _LocalStore:
    """In-process store with TCPStore semantics, used when world_size == 1
    (self-sends, and multi-"rank" tests driven from threads)."""

    def __init__(self):
        self._d: dict[str, bytes] = {}
        self._cv = threading.Condition()

    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        with self._cv:
            self._d[key] = bytes(value)
            self._cv.notify_all()

    def get(self, key):
        with self._cv:
            self._cv.wait_for(lambda: key in self._d, timeout=60)
            return self._d[key]

    def wait(self, key):
        with self._cv:
            if not self._cv.wait_for(lambda: key in self._d, timeout=60):
                raise TimeoutError(f"store wait timed out on {key!r}")

    def add(self, key, delta):
        with self._cv:
            cur = int.from_bytes(self._d.get(key, b"\0" * 8), "little",
                                 signed=True)
            cur += int(delta)
            self._d[key] = cur.to_bytes(8, "little", signed=True)
            self._cv.notify_all()
            return cur

    def check(self, key):
        with self._cv:
            return key in self._d

    def delete_key(self, key):
        with self._cv:
            self._d.pop(key, None)

    def close(self):
        pass


_STORE = None
_STORE_LOCK = threading.Lock()


def _get_store():
    """Lazily connect to the job's TCPStore (PADDLE_MASTER env from the
    launch contract — distributed/launch). Falls back to an in-process
    store for world_size == 1."""
    global _STORE
    with _STORE_LOCK:
        if _STORE is not None:
            return _STORE
        master = env_str("PADDLE_MASTER", "") or None
        if _single_process() or not master:
            if not _single_process():
                raise RuntimeError(
                    "point-to-point / subset-group eager comm needs the "
                    "TCPStore rendezvous: launch with paddle_tpu.distributed."
                    "launch (sets PADDLE_MASTER) or set PADDLE_MASTER="
                    "host:port")
            _STORE = _LocalStore()
            return _STORE
        from ..core.native_api import TCPStore
        host, port = master.rsplit(":", 1)
        _STORE = TCPStore(host, int(port), is_master=_my_rank() == 0,
                          world_size=_world_size())
        return _STORE


def _pack(arr) -> bytes:
    a = np.asarray(arr)
    buf = io.BytesIO()
    # npy format keeps dtype (incl. bfloat16 via jax's ml_dtypes) + shape
    if a.dtype == jnp.bfloat16:
        np.save(buf, a.view(np.uint16))
        return b"BF16" + buf.getvalue()
    np.save(buf, a)
    return b"RAW0" + buf.getvalue()


def _unpack(data: bytes):
    tag, body = data[:4], data[4:]
    a = np.load(io.BytesIO(body))
    if tag == b"BF16":
        a = a.view(jnp.bfloat16)
    return jnp.asarray(a)


# per-(src,dst) monotonically increasing sequence numbers so repeated
# sends/recvs between the same pair match deterministically
_SEND_SEQ: dict[tuple, int] = {}
_RECV_SEQ: dict[tuple, int] = {}
_SEQ_LOCK = threading.Lock()


class Task:
    """Async handle returned by isend/irecv (paddle task.wait() parity)."""

    def __init__(self, thread: Optional[threading.Thread] = None,
                 result_box: Optional[list] = None):
        self._thread = thread
        self._box = result_box

    def wait(self, timeout=None):
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise TimeoutError("p2p task did not complete")
            self._thread = None
        if self._box and isinstance(self._box[0], BaseException):
            raise self._box[0]
        return True

    def is_completed(self):
        return self._thread is None or not self._thread.is_alive()


def send(tensor, dst=0, group=None, sync_op=True):
    """Host-level point-to-point send over the TCPStore transport.
    ``dst`` is the GLOBAL rank (reference semantics, same convention as
    broadcast/scatter); ``group`` only namespaces the exchange."""
    _warn_if_bulk(_val(tensor), "send")
    store = _get_store()
    src = _my_rank()
    gid = group.id if group else 0
    with _SEQ_LOCK:
        seq = _SEND_SEQ.get((gid, src, dst), 0)
        _SEND_SEQ[(gid, src, dst)] = seq + 1
    store.set(f"p2p/{gid}/{src}->{dst}/{seq}", _pack(_val(tensor)))
    return None


def recv(tensor, src=0, group=None, sync_op=True):
    """Blocking receive matching :func:`send` from GLOBAL rank ``src``."""
    store = _get_store()
    me = _my_rank()
    gid = group.id if group else 0
    with _SEQ_LOCK:
        seq = _RECV_SEQ.get((gid, src, me), 0)
        _RECV_SEQ[(gid, src, me)] = seq + 1
    key = f"p2p/{gid}/{src}->{me}/{seq}"
    store.wait(key)
    v = _unpack(store.get(key))
    store.delete_key(key)
    if isinstance(tensor, Tensor):
        tensor._update_value(v.astype(_val(tensor).dtype)
                             if v.dtype != _val(tensor).dtype else v)
        return tensor
    return Tensor(v)


def _async(fn, *args, **kw):
    box = [None]

    def run():
        try:
            box[0] = fn(*args, **kw)
        except BaseException as e:  # surfaced in Task.wait
            box[0] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return Task(t, box)


def isend(tensor, dst=0, group=None):
    return _async(send, tensor, dst, group)


def irecv(tensor, src=0, group=None):
    return _async(recv, tensor, src, group)


@dataclasses.dataclass
class P2POp:
    op: object
    tensor: object
    peer: int
    group: object = None


def batch_isend_irecv(p2p_op_list):
    """Issue a batch of P2POps concurrently; returns list of Tasks.

    Sends are issued first (store sets never block), then receives — the
    standard deadlock-free ordering for symmetric exchange patterns."""
    for p in p2p_op_list:
        if p.op not in (send, isend, recv, irecv):
            raise ValueError(
                f"P2POp.op must be send/isend/recv/irecv, got {p.op}")
    tasks = []
    for p in p2p_op_list:
        if p.op in (send, isend):
            tasks.append(_async(send, p.tensor, p.peer, p.group))
    for p in p2p_op_list:
        if p.op in (recv, irecv):
            tasks.append(_async(recv, p.tensor, p.peer, p.group))
    return tasks


# --------------------------------------------------------------------------
# store-based subset-group collectives
# --------------------------------------------------------------------------

_COLL_SEQ: dict[tuple, int] = {}


def _coll_round(group, op_name, me) -> int:
    # keyed per member rank: counters advance in lock-step across members
    # whether they live in separate processes or threads of one process
    with _SEQ_LOCK:
        k = (group.id, op_name, me)
        seq = _COLL_SEQ.get(k, 0)
        _COLL_SEQ[k] = seq + 1
        return seq


_BULK_WARNED_OPS: set = set()


def _warn_if_bulk(value, op_name):
    """Size guard for the store transport (VERDICT r4 next #9).

    The store path is a CONTROL-PLANE transport (pickle over the TCP
    store, O(world) per member) — bulk tensor exchange belongs inside
    jit where XLA collectives ride ICI. Configurable:

    - ``PT_EAGER_COLLECTIVE_WARN_MB`` (default 1): threshold in MB.
    - ``PT_EAGER_COLLECTIVE_GUARD``: ``warn`` (default, once per op
      name), ``error`` (raise RuntimeError), or ``off``.
    """
    mode = env_str("PT_EAGER_COLLECTIVE_GUARD", "warn")
    if mode == "off":
        return
    try:
        nbytes = int(np.asarray(value).nbytes)
    except Exception:
        return
    try:
        limit_mb = env_float("PT_EAGER_COLLECTIVE_WARN_MB", 1.0)
    except ValueError:      # guard path: malformed knob must not raise
        limit_mb = 1.0
    if nbytes <= limit_mb * 1e6:
        return
    msg = (f"eager {op_name} of {nbytes / 1e6:.1f} MB rides the host "
           "TCP store (control-plane transport, O(world) per member); "
           "for bulk data use collectives inside jit/shard_map where "
           "XLA lowers them to ICI. Set PT_EAGER_COLLECTIVE_GUARD="
           "error to raise, =off to silence, or "
           "PT_EAGER_COLLECTIVE_WARN_MB to tune the threshold")
    if mode == "error":
        raise RuntimeError(msg)
    if op_name not in _BULK_WARNED_OPS:
        _BULK_WARNED_OPS.add(op_name)
        import warnings
        warnings.warn(msg, RuntimeWarning, stacklevel=3)


def _store_gather(value, group, op_name):
    """All group members contribute `value`; returns the list of all
    members' values ordered by group.ranks. Last reader cleans up."""
    _warn_if_bulk(value, op_name)
    store = _get_store()
    me = group.rank
    rnd = _coll_round(group, op_name, me)
    if me < 0:
        raise RuntimeError(
            f"rank {_my_rank()} called {op_name} on {group} it is not a "
            f"member of")
    base = f"coll/{group.id}/{op_name}/{rnd}"
    store.set(f"{base}/{me}", _pack(value))
    outs = []
    for r in range(group.nranks):
        key = f"{base}/{r}"
        store.wait(key)
        outs.append(_unpack(store.get(key)))
    done = store.add(f"{base}/done", 1)
    if done == group.nranks:
        for r in range(group.nranks):
            store.delete_key(f"{base}/{r}")
        store.delete_key(f"{base}/done")
    return outs


def _reduce_terms(op, parts):
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        out = sum(parts[1:], parts[0])
        return out / len(parts) if op == ReduceOp.AVG else out
    if op == ReduceOp.MAX:
        return jax.tree.reduce(jnp.maximum, parts)
    if op == ReduceOp.MIN:
        return jax.tree.reduce(jnp.minimum, parts)
    out = parts[0]
    for p in parts[1:]:
        out = out * p
    return out


def _use_multihost(group) -> bool:
    """Multihost fast path is valid only when the group is the world AND
    jax itself was initialized multi-process (jax.distributed). On
    TCPStore-only jobs (each worker a 1-process jax runtime) world
    collectives must ride the store too."""
    return _is_world(group) and jax.process_count() == _world_size()


def _gather_all(v, group, op_name):
    """Gather `v` from every member of `group`, ordered by group rank.

    World groups take the multihost fast path when jax is multi-process;
    everything else rides the store so non-members need not participate."""
    if _single_process() and _is_world(group):
        return [v]
    if _use_multihost(group):
        from jax.experimental import multihost_utils
        g = multihost_utils.process_allgather(v)
        return [jnp.asarray(g[i]) for i in range(_world_size())]
    return _store_gather(v, group or _world(), op_name)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    v = _val(tensor)
    parts = _gather_all(v, group, f"allreduce_{op}")
    if len(parts) == 1:
        return tensor
    out = _reduce_terms(op, parts)
    tensor._update_value(out.astype(v.dtype))
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    parts = _gather_all(_val(tensor), group, "allgather")
    tensor_list.extend(Tensor(p) for p in parts)
    return tensor_list


def all_gather_object(object_list, obj, group=None):
    if _single_process() and _is_world(group):
        object_list.append(obj)
        return object_list
    data = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    if _use_multihost(group):
        from jax.experimental import multihost_utils
        n = np.array([data.size], np.int32)
        sizes = multihost_utils.process_allgather(jnp.asarray(n))
        maxn = int(np.max(sizes))
        padded = np.zeros(maxn, np.uint8)
        padded[:data.size] = data
        rows = multihost_utils.process_allgather(jnp.asarray(padded))
        for row, size in zip(rows, np.asarray(sizes).reshape(-1)):
            object_list.append(
                pickle.loads(bytes(np.asarray(row)[:int(size)])))
        return object_list
    rows = _store_gather(data, group or _world(), "allgather_obj")
    object_list.extend(pickle.loads(bytes(np.asarray(r))) for r in rows)
    return object_list


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    g = group or _world()
    if _single_process() and _is_world(group):
        tensor._update_value(_val(tensor_list[0]))
        return tensor
    stacked = jnp.stack([_val(t) for t in tensor_list])
    parts = _gather_all(stacked, g, f"reducescatter_{op}")
    total = _reduce_terms(op, parts)
    me = g.rank if not _is_world(g) else _my_rank()
    tensor._update_value(total[me])
    return tensor


def broadcast(tensor, src=0, group=None, sync_op=True):
    if _single_process() and _is_world(group):
        return tensor
    v = _val(tensor)
    if _use_multihost(group):
        from jax.experimental import multihost_utils
        out = multihost_utils.broadcast_one_to_all(
            v, is_source=_my_rank() == src)
        tensor._update_value(jnp.asarray(out))
        return tensor
    # store path: src is the GLOBAL rank (reference semantics)
    g = group or _world()
    parts = _store_gather(v, g, "broadcast")
    idx = g.get_group_rank(src)
    if idx < 0:
        raise ValueError(f"broadcast src={src} is not a member of {g}")
    tensor._update_value(parts[idx].astype(v.dtype))
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    all_reduce(tensor, op, group, sync_op)  # reduce-to-all ⊇ reduce
    return tensor


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """Collect ``tensor`` from every rank into ``gather_list`` on rank
    ``dst`` (reference: paddle.distributed.gather — verify). Other
    ranks leave ``gather_list`` untouched. Control-plane transport like
    the other eager collectives; bulk data belongs inside jitted
    programs."""
    g = group or _world()
    if _single_process() and _is_world(group):
        if gather_list is not None:
            gather_list.append(Tensor(_val(tensor)))
        return gather_list
    parts = _store_gather(_val(tensor), g, "gather")
    idx = g.get_group_rank(dst)
    if idx < 0:
        raise ValueError(f"gather dst={dst} is not a member of {g}")
    me = g.rank if not _is_world(g) else _my_rank()
    if me == idx and gather_list is not None:
        gather_list.extend(Tensor(jnp.asarray(p)) for p in parts)
    return gather_list


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    g = group or _world()
    if _single_process() and _is_world(group):
        if tensor_list:
            tensor._update_value(_val(tensor_list[0]))
        return tensor
    stacked = jnp.stack([_val(t) for t in tensor_list]) if tensor_list \
        else jnp.zeros((g.nranks,) + tuple(tensor.shape), tensor.dtype)
    if _use_multihost(group):
        from jax.experimental import multihost_utils
        v = multihost_utils.broadcast_one_to_all(
            stacked, is_source=_my_rank() == src)
        tensor._update_value(jnp.asarray(v)[_my_rank()])
        return tensor
    parts = _store_gather(stacked, g, "scatter")
    idx = g.get_group_rank(src)
    if idx < 0:
        raise ValueError(f"scatter src={src} is not a member of {g}")
    tensor._update_value(parts[idx][g.rank])
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    g = group or _world()
    if out_tensor_list is None:
        out_tensor_list = []
    if _single_process() and _is_world(group):
        out_tensor_list.extend(Tensor(_val(t)) for t in in_tensor_list)
        return out_tensor_list
    stacked = jnp.stack([_val(t) for t in in_tensor_list])
    rows = _gather_all(stacked, g, "alltoall")  # [nranks](nranks, ...)
    me = g.rank if not _is_world(g) else _my_rank()
    for p in range(len(rows)):
        out_tensor_list.append(Tensor(jnp.asarray(rows[p][me])))
    return out_tensor_list


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    g = group or _world()
    n = 1 if (_single_process() and _is_world(group)) else g.nranks
    parts = jnp.split(_val(in_tensor), n)
    outs = alltoall([Tensor(p) for p in parts], group=group)
    res = jnp.concatenate([_val(t) for t in outs])
    if out_tensor is not None:
        out_tensor._update_value(res)
        return out_tensor
    return Tensor(res)


def global_scatter(x, local_count, global_count, group=None, sync_op=True):
    """MoE expert exchange (reference parity: paddle.distributed.utils.
    global_scatter / paddle/fluid/operators/collective/global_scatter_op.*
    — verify).

    ``x`` rows are grouped by GLOBAL expert id with ``local_count[i]``
    rows destined for expert ``i`` (experts are owned round-robin-block:
    rank r owns experts [r*e_per, (r+1)*e_per), e_per = E/nranks). Each
    rank receives the rows for ITS experts from every rank, ordered
    (local_expert, src_rank) — the reference's layout.

    Eager control-plane shim over the object-exchange path (variable row
    counts per destination make this a ragged alltoall). The COMPILED
    hot path is MoELayer's dual-map gather dispatch, where GSPMD inserts
    the equivalent all-to-all over the "ep" mesh axis — use that for
    training steps; this API exists for reference-parity orchestration
    and tests."""
    import numpy as np
    g = group or _world()
    lc = [int(v) for v in np.asarray(_val(local_count)).reshape(-1)]
    xv = np.asarray(_val(x))
    nranks = 1 if (_single_process() and _is_world(group)) else g.nranks
    if len(lc) % nranks:
        raise ValueError(
            f"local_count length {len(lc)} not divisible by world size "
            f"{nranks}")
    e_per = len(lc) // nranks
    # split x into per-global-expert blocks
    offs = np.cumsum([0] + lc)
    if offs[-1] != xv.shape[0]:
        raise ValueError(
            f"sum(local_count)={offs[-1]} != rows of x {xv.shape[0]}")
    blocks = [xv[offs[i]:offs[i + 1]] for i in range(len(lc))]
    if nranks == 1:
        return Tensor(jnp.asarray(np.concatenate(blocks)
                                  if blocks else xv))
    gathered = []
    all_gather_object(gathered, blocks, group=g)
    me = g.rank if not _is_world(g) else _my_rank()
    out = []
    for i_local in range(e_per):
        for r in range(nranks):
            out.append(gathered[r][me * e_per + i_local])
    res = np.concatenate(out) if out else xv[:0]
    return Tensor(jnp.asarray(res))


def global_gather(x, local_count, global_count, group=None, sync_op=True):
    """Inverse of :func:`global_scatter` (reference parity:
    global_gather_op.* — verify): rows grouped (local_expert, src_rank)
    with ``global_count[i_local*nranks + r]`` rows from rank ``r`` are
    returned to their source ranks, restoring the sender's
    global-expert-id grouping described by ``local_count``."""
    import numpy as np
    g = group or _world()
    gc = [int(v) for v in np.asarray(_val(global_count)).reshape(-1)]
    lc = [int(v) for v in np.asarray(_val(local_count)).reshape(-1)]
    xv = np.asarray(_val(x))
    nranks = 1 if (_single_process() and _is_world(group)) else g.nranks
    if len(lc) != len(gc):
        raise ValueError(
            f"local_count length {len(lc)} != global_count length "
            f"{len(gc)} (both must cover all E experts)")
    if len(gc) % nranks:
        raise ValueError(
            f"global_count length {len(gc)} not divisible by world size "
            f"{nranks}")
    e_per = len(gc) // nranks
    offs = np.cumsum([0] + gc)
    if offs[-1] != xv.shape[0]:
        raise ValueError(
            f"sum(global_count)={offs[-1]} != rows of x {xv.shape[0]}")
    # block (i_local, r) = rows received from rank r for my expert i_local
    blocks = [xv[offs[i]:offs[i + 1]] for i in range(len(gc))]
    if nranks == 1:
        return Tensor(jnp.asarray(np.concatenate(blocks)
                                  if blocks else xv))
    gathered = []
    all_gather_object(gathered, blocks, group=g)
    me = g.rank if not _is_world(g) else _my_rank()
    # my original send order: for each global expert i (owner o, slot
    # i_local), my block sits at position (i_local, me) in o's buffer
    out = []
    for i in range(len(lc)):
        owner, i_local = divmod(i, e_per)
        out.append(gathered[owner][i_local * nranks + me])
    res = np.concatenate(out) if out else xv[:0]
    return Tensor(jnp.asarray(res))


def barrier(group=None):
    if _single_process() and _is_world(group):
        return
    if _use_multihost(group):
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("paddle_tpu_barrier")
        return
    _store_gather(jnp.zeros((), jnp.int32), group or _world(), "barrier")


def wait(tensor, group=None, use_calc_stream=True):
    v = _val(tensor)
    if hasattr(v, "block_until_ready"):
        v.block_until_ready()


class stream:
    """paddle.distributed.stream.* namespace: same ops, async handles."""
    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    reduce_scatter = staticmethod(reduce_scatter)
    broadcast = staticmethod(broadcast)
    alltoall = staticmethod(alltoall)
    send = staticmethod(send)
    recv = staticmethod(recv)
