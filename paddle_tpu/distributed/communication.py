"""Eager collective communication API.

Reference parity: python/paddle/distributed/communication/ +
paddle/phi/core/distributed/ProcessGroup* (NCCL) — verify.

TPU-native design: the *perf path* never calls these eagerly — GSPMD emits
collectives inside jitted programs over the mesh (SURVEY §2.4). This module
provides the paddle-compatible eager API for host-level coordination and
tests: across processes it lowers to jax multihost utilities (which run tiny
XLA collective programs over DCN/ICI); with one process and a sharded
array, the "group" is a mesh axis and the op runs as a tiny jitted
shard_map collective."""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor

__all__ = ["ReduceOp", "Group", "all_reduce", "all_gather",
           "all_gather_object", "reduce_scatter", "broadcast", "scatter",
           "reduce", "alltoall", "alltoall_single", "send", "recv",
           "barrier", "new_group", "get_group", "wait", "stream", "P2POp",
           "batch_isend_irecv", "isend", "irecv"]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    def __init__(self, ranks, gid=0, name=None):
        self.ranks = list(ranks)
        self.id = gid
        self.name = name or f"group_{gid}"

    @property
    def nranks(self):
        return len(self.ranks)

    @property
    def world_size(self):
        return len(self.ranks)

    @property
    def rank(self):
        pid = jax.process_index()
        return self.ranks.index(pid) if pid in self.ranks else -1

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks})"


_GROUPS: dict[int, Group] = {}
_NEXT_GID = [1]


def _world():
    if 0 not in _GROUPS:
        _GROUPS[0] = Group(list(range(jax.process_count())), 0, "world")
    return _GROUPS[0]


def new_group(ranks=None, backend=None, timeout=None):
    gid = _NEXT_GID[0]
    _NEXT_GID[0] += 1
    g = Group(ranks if ranks is not None
              else list(range(jax.process_count())), gid)
    _GROUPS[gid] = g
    return g


def get_group(gid=0):
    return _GROUPS.get(gid, _world())


def _val(t):
    return t._value if isinstance(t, Tensor) else jnp.asarray(t)


def _single_process() -> bool:
    return jax.process_count() == 1


def _reduce_terms(op, parts):
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        out = sum(parts[1:], parts[0])
        return out / len(parts) if op == ReduceOp.AVG else out
    if op == ReduceOp.MAX:
        return jax.tree.reduce(jnp.maximum, parts)
    if op == ReduceOp.MIN:
        return jax.tree.reduce(jnp.minimum, parts)
    out = parts[0]
    for p in parts[1:]:
        out = out * p
    return out


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    if _single_process():
        return tensor  # single process: tensor is already global
    from jax.experimental import multihost_utils
    v = _val(tensor)
    gathered = multihost_utils.process_allgather(v)
    out = _reduce_terms(op, list(gathered))
    tensor._update_value(out.astype(v.dtype))
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    if _single_process():
        tensor_list.append(Tensor(_val(tensor)))
        return tensor_list
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(_val(tensor))
    for row in gathered:
        tensor_list.append(Tensor(jnp.asarray(row)))
    return tensor_list


def all_gather_object(object_list, obj, group=None):
    if _single_process():
        object_list.append(obj)
        return object_list
    import pickle
    from jax.experimental import multihost_utils
    data = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    # pad to max length across processes
    n = np.array([data.size], np.int32)
    sizes = multihost_utils.process_allgather(jnp.asarray(n))
    maxn = int(np.max(sizes))
    padded = np.zeros(maxn, np.uint8)
    padded[:data.size] = data
    rows = multihost_utils.process_allgather(jnp.asarray(padded))
    for row, size in zip(rows, np.asarray(sizes).reshape(-1)):
        object_list.append(pickle.loads(bytes(np.asarray(row)[:int(size)])))
    return object_list


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    if _single_process():
        tensor._update_value(_val(tensor_list[0]))
        return tensor
    from jax.experimental import multihost_utils
    stacked = jnp.stack([_val(t) for t in tensor_list])
    summed = multihost_utils.process_allgather(stacked)
    total = _reduce_terms(op, list(summed))
    tensor._update_value(total[jax.process_index()])
    return tensor


def broadcast(tensor, src=0, group=None, sync_op=True):
    if _single_process():
        return tensor
    from jax.experimental import multihost_utils
    v = multihost_utils.broadcast_one_to_all(
        _val(tensor), is_source=jax.process_index() == src)
    tensor._update_value(jnp.asarray(v))
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    all_reduce(tensor, op, group, sync_op)  # reduce-to-all ⊇ reduce
    return tensor


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if _single_process():
        if tensor_list:
            tensor._update_value(_val(tensor_list[0]))
        return tensor
    from jax.experimental import multihost_utils
    stacked = jnp.stack([_val(t) for t in tensor_list]) if tensor_list \
        else jnp.zeros((jax.process_count(),) + tuple(tensor.shape),
                       tensor.dtype)
    v = multihost_utils.broadcast_one_to_all(
        stacked, is_source=jax.process_index() == src)
    tensor._update_value(jnp.asarray(v)[jax.process_index()])
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    if out_tensor_list is None:
        out_tensor_list = []
    if _single_process():
        out_tensor_list.extend(Tensor(_val(t)) for t in in_tensor_list)
        return out_tensor_list
    from jax.experimental import multihost_utils
    stacked = jnp.stack([_val(t) for t in in_tensor_list])
    rows = multihost_utils.process_allgather(stacked)  # (P, P, ...)
    me = jax.process_index()
    for p in range(jax.process_count()):
        out_tensor_list.append(Tensor(jnp.asarray(rows[p][me])))
    return out_tensor_list


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    parts = jnp.split(_val(in_tensor),
                      jax.process_count() if _single_process() is False
                      else 1)
    outs = alltoall([Tensor(p) for p in parts])
    res = jnp.concatenate([_val(t) for t in outs])
    if out_tensor is not None:
        out_tensor._update_value(res)
        return out_tensor
    return Tensor(res)


def send(tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "point-to-point send/recv across processes uses the launch-level "
        "store; inside compiled programs use shard_map ppermute "
        "(paddle_tpu.distributed.fleet.meta_parallel pipeline)")


def recv(tensor, src=0, group=None, sync_op=True):
    raise NotImplementedError(
        "see send(): use ppermute inside compiled programs")


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group)


@dataclasses.dataclass
class P2POp:
    op: object
    tensor: object
    peer: int
    group: object = None


def batch_isend_irecv(p2p_op_list):
    raise NotImplementedError(
        "host-level batched p2p: planned with the C++ store backend; "
        "compiled pipelines use ppermute schedules instead")


def barrier(group=None):
    if _single_process():
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("paddle_tpu_barrier")


def wait(tensor, group=None, use_calc_stream=True):
    v = _val(tensor)
    if hasattr(v, "block_until_ready"):
        v.block_until_ready()


class stream:
    """paddle.distributed.stream.* namespace: same ops, async handles."""
    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    reduce_scatter = staticmethod(reduce_scatter)
    broadcast = staticmethod(broadcast)
    alltoall = staticmethod(alltoall)
