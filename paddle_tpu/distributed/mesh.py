"""Hybrid-parallel topology: the keystone of the distributed stack.

Reference parity: ``HybridCommunicateGroup``
(python/paddle/distributed/fleet/base/topology.py — verify): builds the
Cartesian dp×pp×sharding×sep×mp process topology and one comm group per
axis.

TPU-native design: ONE ``jax.sharding.Mesh`` whose named axes are the
parallelism dimensions, laid out with ``mesh_utils.create_device_mesh`` so
the innermost axes (mp/sep) ride the fastest ICI links of the v5p torus.
A "communication group" is just (mesh, axis-name); collectives inside
jitted programs reference axis names, never rank lists."""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["HybridCommunicateGroup", "get_hybrid_communicate_group",
           "build_device_mesh", "CommunicateTopology"]

# axis order: outermost (slowest/DCN-adjacent) → innermost (fastest ICI).
# pp stages communicate least per step; mp/sep all-reduce constantly.
AXIS_ORDER = ("pp", "dp", "sharding", "sep", "mp")

_HCG: Optional["HybridCommunicateGroup"] = None
_CURRENT_MESH: Optional[Mesh] = None


def set_current_mesh(mesh: Optional[Mesh]):
    global _CURRENT_MESH
    _CURRENT_MESH = mesh


def get_current_mesh() -> Optional[Mesh]:
    return _CURRENT_MESH


def build_device_mesh(axis_dims: dict, devices=None,
                      allow_subset: bool = False) -> Mesh:
    """axis_dims: {"dp": 2, "mp": 4, ...}; missing axes get degree 1.
    With allow_subset, uses the first prod(dims) devices (driver dryruns);
    otherwise a size mismatch is an error — silently idling chips hides
    config typos."""
    devices = list(devices if devices is not None else jax.devices())
    dims = [int(axis_dims.get(a, 1)) for a in AXIS_ORDER]
    total = int(np.prod(dims))
    if total > len(devices) or (total < len(devices) and not allow_subset):
        raise ValueError(
            f"topology {dict(zip(AXIS_ORDER, dims))} needs {total} devices, "
            f"have {len(devices)} (pass allow_subset=True to use a prefix)")
    devices = devices[:total]
    try:
        from jax.experimental import mesh_utils
        arr = mesh_utils.create_device_mesh(dims, devices=devices)
    except Exception:
        arr = np.array(devices).reshape(dims)
    return Mesh(arr, AXIS_ORDER)


class CommunicateTopology:
    """Parity shim for fleet.base.topology.CommunicateTopology — verify."""

    def __init__(self, hybrid_group_names, dims):
        self._names = list(hybrid_group_names)
        self._dims = list(dims)

    def get_hybrid_group_names(self):
        return self._names

    def get_dim(self, name):
        return self._dims[self._names.index(name)]

    def world_size(self):
        return int(np.prod(self._dims))


class HybridCommunicateGroup:
    def __init__(self, dp_degree=1, mp_degree=1, pp_degree=1,
                 sharding_degree=1, sep_degree=1, order=None, devices=None,
                 allow_subset=False):
        self._dims = {"dp": dp_degree, "mp": mp_degree, "pp": pp_degree,
                      "sharding": sharding_degree, "sep": sep_degree}
        self.mesh = build_device_mesh(self._dims, devices,
                                      allow_subset=allow_subset)
        self._topo = CommunicateTopology(list(AXIS_ORDER),
                                         [self._dims.get(a, 1)
                                          for a in AXIS_ORDER])
        global _HCG
        _HCG = self
        set_current_mesh(self.mesh)

    # -- mesh-native accessors ---------------------------------------------
    @property
    def jax_mesh(self) -> Mesh:
        return self.mesh

    def axis_size(self, axis: str) -> int:
        return self._dims.get(axis, 1)

    def sharding_spec(self, *axes) -> PartitionSpec:
        return PartitionSpec(*axes)

    def named_sharding(self, *axes) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec(*axes))

    # -- paddle fleet.topology API parity ----------------------------------
    def get_parallel_mode(self):
        if self._dims["pp"] > 1:
            return "pipeline_parallel"
        if self._dims["sharding"] > 1:
            return "sharding_parallel"
        if self._dims["mp"] > 1:
            return "tensor_parallel"
        return "data_parallel"

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return jax.process_index()

    # world sizes
    def get_data_parallel_world_size(self):
        return self._dims["dp"]

    def get_model_parallel_world_size(self):
        return self._dims["mp"]

    def get_pipe_parallel_world_size(self):
        return self._dims["pp"]

    def get_sharding_parallel_world_size(self):
        return self._dims["sharding"]

    def get_sep_parallel_world_size(self):
        return self._dims["sep"]

    # ranks: under single-controller SPMD there is one logical program; the
    # per-axis "rank" is meaningful only inside shard_map — expose 0 host-side
    # (multi-host: derive from process index position in the mesh).
    def _axis_rank(self, axis):
        if jax.process_count() == 1:
            return 0
        # position of this process's first device along the axis
        coords = np.argwhere(
            np.vectorize(lambda d: d.process_index)(self.mesh.devices)
            == jax.process_index())
        if coords.size == 0:
            return 0
        return int(coords[0][list(AXIS_ORDER).index(axis)])

    def get_data_parallel_rank(self):
        return self._axis_rank("dp")

    def get_model_parallel_rank(self):
        return self._axis_rank("mp")

    def get_stage_id(self):
        return self._axis_rank("pp")

    def get_sharding_parallel_rank(self):
        return self._axis_rank("sharding")

    def get_sep_parallel_rank(self):
        return self._axis_rank("sep")

    # group objects (API parity; value = (mesh, axis))
    class _AxisGroup:
        def __init__(self, mesh, axis, size):
            self.mesh = mesh
            self.axis = axis
            self.nranks = size
            self.world_size = size
            self.rank = 0

        @property
        def ranks(self):
            return list(range(self.nranks))

    def _group(self, axis):
        return self._AxisGroup(self.mesh, axis, self._dims.get(axis, 1))

    def get_data_parallel_group(self):
        return self._group("dp")

    def get_model_parallel_group(self):
        return self._group("mp")

    def get_pipe_parallel_group(self):
        return self._group("pp")

    def get_sharding_parallel_group(self):
        return self._group("sharding")

    def get_sep_parallel_group(self):
        return self._group("sep")

    def get_check_parallel_group(self, *a, **k):
        return self._group("mp")

    def get_data_parallel_group_src_rank(self):
        return 0

    def get_model_parallel_group_src_rank(self):
        return 0

    # pipeline helpers
    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._dims["pp"] - 1


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _HCG
