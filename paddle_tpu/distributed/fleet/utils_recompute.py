"""Activation recompute (reference:
python/paddle/distributed/fleet/recompute/recompute.py — PyLayer that
re-runs forward in backward — verify).

TPU-native design: ``jax.checkpoint`` — the compiler reruns the forward in
the backward pass, with a policy hook for selective recompute (dots
saveable). Eager mode just calls through (the tape holds residuals)."""
from __future__ import annotations

import jax

from ... import framework
from ...tensor import Tensor

__all__ = ["recompute", "recompute_sequential"]


def recompute(function, *args, use_reentrant=True, preserve_rng_state=True,
              policy=None, **kwargs):
    if not framework.in_functional_mode():
        return function(*args, **kwargs)

    tensor_pos = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    vals = tuple(args[i]._value for i in tensor_pos)
    holder = {}

    def pure(*tvals):
        full = list(args)
        for p, v in zip(tensor_pos, tvals):
            full[p] = Tensor(v)
        out = function(*full, **kwargs)
        leaves, tree = jax.tree.flatten(
            out, is_leaf=lambda x: isinstance(x, Tensor))
        holder["tree"] = tree
        return tuple(l._value if isinstance(l, Tensor) else l
                     for l in leaves)

    ckpt_kwargs = {}
    if policy is not None:
        ckpt_kwargs["policy"] = policy
    out_vals = jax.checkpoint(pure, **ckpt_kwargs)(*vals)
    return jax.tree.unflatten(holder["tree"],
                              [Tensor(v) for v in out_vals])


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Recompute a Sequential in segments (reference:
    recompute_sequential — verify). ctx: {"segments": n}."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else ctx
    funcs = list(functions)
    seg_size = max(1, len(funcs) // segments)

    def make_seg(fs):
        def seg_forward(x):
            for f in fs:
                x = f(x)
            return x
        return seg_forward

    x = args[0]
    for s in range(0, len(funcs), seg_size):
        x = recompute(make_seg(funcs[s:s + seg_size]), x)
    return x
