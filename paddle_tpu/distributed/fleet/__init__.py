"""Fleet: collective hybrid-parallel orchestration.

Reference parity: python/paddle/distributed/fleet/ (fleet.init,
DistributedStrategy.hybrid_configs, distributed_model/optimizer — verify).

TPU-native design: ``fleet.init`` builds the HybridCommunicateGroup (ONE jax
Mesh with pp/dp/sharding/sep/mp axes). ``distributed_model`` annotates
parameters with partition specs per strategy (TP layers carry their own);
``distributed_optimizer`` wires sharding (ZeRO) by re-placing optimizer
slots. The compiled TrainStep consumes these annotations and GSPMD emits
all collectives."""
from __future__ import annotations

from typing import Optional

import jax

from ..mesh import HybridCommunicateGroup, get_hybrid_communicate_group
from ..parallel import DataParallel
from . import meta_parallel
from . import meta_optimizers
from . import utils                                        # noqa
from .meta_parallel import (ColumnParallelLinear, RowParallelLinear,
                            VocabParallelEmbedding, ParallelCrossEntropy,
                            PipelineLayer, LayerDesc, SharedLayerDesc)  # noqa
from ...nn.layer import Layer

__all__ = ["init", "DistributedStrategy", "distributed_model",
           "distributed_optimizer", "get_hybrid_communicate_group",
           "worker_num", "worker_index", "is_first_worker", "barrier_worker",
           "meta_parallel", "utils"]

_FLEET = {"initialized": False, "strategy": None, "hcg": None}


class DistributedStrategy:
    """Reference: protobuf-backed DistributedStrategy (fleet/base/
    distributed_strategy.py — verify). Plain attrs here."""

    def __init__(self):
        self.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 1,
                               "sep_degree": 1}
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "micro_batch_size": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.tensor_parallel_configs = {}
        self.find_unused_parameters = False

    def __setattr__(self, k, v):
        if k == "hybrid_configs" and hasattr(self, "hybrid_configs"):
            merged = dict(self.__dict__.get("hybrid_configs", {}))
            merged.update(v)
            object.__setattr__(self, k, merged)
        else:
            object.__setattr__(self, k, v)


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    if strategy is None:
        strategy = DistributedStrategy()
    h = strategy.hybrid_configs
    n_dev = len(jax.devices())
    degrees = {k: int(h.get(k, 1)) for k in
               ("dp_degree", "mp_degree", "pp_degree", "sharding_degree",
                "sep_degree")}
    # paddle convention: dp_degree=-1 → infer from world size
    known = 1
    for k, v in degrees.items():
        if v > 0 and k != "dp_degree":
            known *= v
    if degrees["dp_degree"] in (-1, 0):
        degrees["dp_degree"] = max(n_dev // known, 1)
    hcg = HybridCommunicateGroup(
        dp_degree=degrees["dp_degree"], mp_degree=degrees["mp_degree"],
        pp_degree=degrees["pp_degree"],
        sharding_degree=degrees["sharding_degree"],
        sep_degree=degrees["sep_degree"])
    _FLEET.update(initialized=True, strategy=strategy, hcg=hcg)
    return None


def _require_init():
    if not _FLEET["initialized"]:
        raise RuntimeError("call fleet.init(...) first")


def get_strategy():
    return _FLEET["strategy"]


def distributed_model(model: Layer):
    """Annotate the model for the active hybrid strategy. TP layers
    (ColumnParallelLinear...) already carry mp partition specs; here we add
    FSDP ("sharding" axis) placement for remaining params and return a
    DataParallel façade when dp is active (reference: fleet.Fleet.
    distributed_model wrapping TensorParallel/PipelineParallel/... — verify)"""
    _require_init()
    hcg = _FLEET["hcg"]
    if hcg.axis_size("sharding") > 1:
        from ..mesh import get_current_mesh
        from ..sharding import _sharded_spec
        mesh = get_current_mesh()
        for name, p in model.named_parameters():
            if p._sharding_spec is None and p._value.ndim >= 1 and \
                    mesh is not None:
                spec = _sharded_spec(p._value.shape, "sharding", mesh)
                if spec is not None:
                    p._sharding_spec = spec
    if isinstance(model, PipelineLayer):
        from ..pipeline import PipelineParallel
        return PipelineParallel(model, hcg=hcg,
                                strategy=_FLEET["strategy"])
    if hcg.axis_size("dp") > 1:
        return DataParallel(model)
    return model


def distributed_optimizer(optimizer, strategy=None):
    """Apply ZeRO sharding per strategy (reference: HybridParallelOptimizer
    + DygraphShardingOptimizer — fleet/meta_optimizers/dygraph_optimizer/
    — verify). Stage from sharding_configs{"stage": 1|2|3}; any active
    sharding axis defaults to stage 1 (optimizer-state sharding)."""
    _require_init()
    strategy = strategy or _FLEET["strategy"]
    hcg = _FLEET["hcg"]
    stage = 0
    if strategy is not None and getattr(strategy, "sharding", False):
        stage = int(strategy.sharding_configs.get("stage", 1))
    elif hcg.axis_size("sharding") > 1:
        stage = 1
    if stage:
        from ..sharding import group_sharded_parallel
        level = {1: "os", 2: "os_g", 3: "p_g_os"}[min(stage, 3)]
        # model-side placement (stage 3) is handled by distributed_model;
        # here only the optimizer hooks are attached
        _, optimizer, _ = group_sharded_parallel(None, optimizer, level)
    # strategy flags -> meta-optimizer wrappers (reference: the
    # meta_optimizers pass stack applied by fleet per strategy)
    if strategy is not None and getattr(strategy, "gradient_merge",
                                        False):
        cfg = getattr(strategy, "gradient_merge_configs", {}) or {}
        optimizer = meta_optimizers.GradientMergeOptimizer(
            optimizer, k_steps=int(cfg.get("k_steps", 1)),
            avg=bool(cfg.get("avg", True)))
    if strategy is not None and getattr(strategy, "amp", False):
        cfg = getattr(strategy, "amp_configs", {}) or {}
        optimizer = meta_optimizers.AMPOptimizer(
            optimizer, dtype=cfg.get("dtype", "bfloat16"),
            init_loss_scaling=float(
                cfg.get("init_loss_scaling", 2.**15)))
    return optimizer


def worker_num():
    return jax.process_count()


def worker_index():
    return jax.process_index()


def is_first_worker():
    return jax.process_index() == 0


def barrier_worker():
    from ..communication import barrier
    barrier()


# NOTE: `utils` is the real module imported at the top (fleet/utils.py:
# fused_allreduce_gradients, recompute, recompute_sequential) — it must
# NOT be shadowed here; an earlier namespace object hid everything but
# recompute from attribute access (import statements still found the
# module via sys.modules, so the break was path-dependent).
