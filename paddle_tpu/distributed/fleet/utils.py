"""Fleet training-loop utilities (reference:
python/paddle/distributed/fleet/utils/hybrid_parallel_util.py — verify).

TPU-native note: inside a jitted TrainStep, gradient synchronization is
GSPMD's job (grads of replicated params are psum'd automatically). These
helpers serve MANUAL eager loops ported from the reference, where the
user calls fused_allreduce_gradients between backward() and opt.step().
"""
from __future__ import annotations

from ...tensor import Tensor

__all__ = ["fused_allreduce_gradients", "recompute", "recompute_sequential"]

from .utils_recompute import recompute, recompute_sequential  # noqa: F401


def fused_allreduce_gradients(parameter_list, hcg=None, group=None,
                              bucket_bytes=None):
    """All-reduce (mean) every parameter's gradient across the data-
    parallel group (reference: fused_allreduce_gradients — the bucketed
    NCCL allreduce the C++ Reducer performs; the jitted path needs none
    of this). Default: one host-level all_reduce per grad. With
    ``bucket_bytes`` set — or the collectives config flag
    ``bucketed_grad_sync`` on — gradients coalesce into size-targeted
    fusion buffers and sync one bucket at a time (same values, O(params)
    -> O(buckets) rendezvous rounds on the store transport)."""
    from .. import communication as C

    if hcg is not None and group is None:
        get = getattr(hcg, "get_data_parallel_group", None)
        if callable(get):
            try:
                group = get()
            except Exception:
                group = None
    from ..collectives import collective_config
    if bucket_bytes is not None or \
            collective_config().bucketed_grad_sync:
        from ..collectives import bucketed_allreduce_gradients
        return bucketed_allreduce_gradients(
            parameter_list, group=group, bucket_bytes=bucket_bytes)
    n = None
    for p in parameter_list:
        if not isinstance(p, Tensor) or p.grad is None:
            continue
        C.all_reduce(p.grad, op=C.ReduceOp.SUM, group=group)
        if n is None:
            if group is not None and getattr(group, "nranks", 0):
                n = group.nranks
            else:
                from ..parallel import ParallelEnv
                n = max(ParallelEnv().world_size, 1)
        if n > 1:
            p.grad._update_value(p.grad._value / n)
