"""fleet.meta_optimizers — reference parity namespace
(python/paddle/distributed/fleet/meta_optimizers/ — verify).

The reference's meta-optimizers are static-graph program rewriters
(AMP pass, recompute pass, gradient-merge pass) stacked by
DistributedStrategy flags. Here the same capabilities are functional
wrappers over the inner optimizer / model:

  - GradientMergeOptimizer: REAL k-step gradient accumulation — grads
    sum on device across k micro-steps (optionally averaged), the inner
    optimizer steps once per k, clear_grad between micro-steps is a
    no-op for merged params so the accumulator survives the user's
    standard train loop.
  - RecomputeOptimizer: pairs with `fleet.utils.recompute` — holds the
    inner optimizer and exposes the reference's API shape (the actual
    rematerialization is jax.checkpoint at the layer, SURVEY §7).
  - AMPOptimizer: wraps with `amp.decorate` semantics — scales via
    GradScaler when fp16, plain bf16 otherwise.

These also back DistributedStrategy's gradient_merge/amp/recompute
flags in fleet.distributed_optimizer.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["GradientMergeOptimizer", "RecomputeOptimizer",
           "AMPOptimizer"]


class _MetaBase:
    def __init__(self, inner):
        self.inner_opt = inner

    def __getattr__(self, name):
        return getattr(self.inner_opt, name)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """Route through THIS wrapper's step() — delegating minimize to
        the inner optimizer would silently bypass accumulation/scaling
        (the reference meta-optimizers own minimize for the same
        reason). Static-graph mode delegates to the inner optimizer:
        there the capability comes from the Engine pass pipeline, and
        an eager backward() would break program capture. Returns the
        base (None, None) contract."""
        from ... import framework
        if framework.in_static_mode():
            return self.inner_opt.minimize(
                loss, startup_program=startup_program,
                parameters=parameters, no_grad_set=no_grad_set)
        loss.backward()
        self.step()
        return None, None


class GradientMergeOptimizer(_MetaBase):
    """k-step gradient accumulation (reference: gradient_merge pass /
    GradientMergeOptimizer — verify).

        opt = GradientMergeOptimizer(inner, k_steps=4, avg=True)
        for batch in loader:
            loss.backward(); opt.step(); opt.clear_grad()

    Only every k-th step() runs the inner optimizer (on the merged
    grads); the others accumulate and return."""

    def __init__(self, inner, k_steps=1, avg=True):
        super().__init__(inner)
        self.k_steps = max(int(k_steps), 1)
        self.avg = bool(avg)
        self._acc: dict[int, object] = {}
        self._micro = 0

    def step(self):
        self._micro += 1
        params = self.inner_opt._param_list
        for p in params:
            if p.grad is None or p.stop_gradient:
                continue
            g = p.grad._value
            aid = id(p)
            acc = self._acc.get(aid)
            self._acc[aid] = g if acc is None else acc + g
            # snapshot-and-clear: backward() ACCUMULATES into p.grad,
            # so leaving the micro-grad there would double-count it on
            # the next micro-step in clear_grad-free loops (minimize);
            # clearing here makes both loop shapes correct
            p.clear_gradient(False)
        if self._micro < self.k_steps:
            return
        # merged step: install accumulated grads, run the inner opt
        from ...tensor import Tensor
        scale = 1.0 / self.k_steps if self.avg else 1.0
        for p in params:
            acc = self._acc.get(id(p))
            if acc is None:
                continue
            p.grad = Tensor(acc * jnp.asarray(scale, acc.dtype))
        self.inner_opt.step()
        self._acc.clear()
        self._micro = 0
        for p in params:
            p.clear_gradient(False)

    def clear_grad(self, set_to_zero=False):
        """Clears only the CURRENT micro-step's grads; the merged
        accumulator lives in this wrapper, so the reference train-loop
        shape (backward/step/clear_grad) accumulates correctly."""
        for p in self.inner_opt._param_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    def state_dict(self):
        """Inner state plus the mid-cycle accumulator — a checkpoint
        taken between merged steps must not drop accumulated grads
        (same precedent as incubate.LookAhead's wrapper slots)."""
        from ...tensor import Tensor
        out = dict(self.inner_opt.state_dict())
        out["@gm_micro"] = self._micro
        names = dict(zip((id(p) for p in self.inner_opt._param_list),
                         self.inner_opt._param_names))
        for aid, acc in self._acc.items():
            n = names.get(aid)
            if n is not None:
                out[f"@gm_acc.{n}"] = Tensor(acc)
        return out

    def set_state_dict(self, state):
        state = dict(state)
        self._micro = int(state.pop("@gm_micro", 0))
        by_name = dict(zip(self.inner_opt._param_names,
                           self.inner_opt._param_list))
        self._acc = {}
        for k in list(state):
            if k.startswith("@gm_acc."):
                n = k[len("@gm_acc."):]
                p = by_name.get(n)
                v = state.pop(k)
                if p is not None:
                    self._acc[id(p)] = getattr(v, "_value", v)
        self.inner_opt.set_state_dict(state)


class RecomputeOptimizer(_MetaBase):
    """API-shape parity (reference: RecomputeOptimizer — verify): the
    rematerialization itself is `fleet.utils.recompute` /
    `recompute_sequential` (jax.checkpoint) applied at the layer;
    this wrapper carries the inner optimizer through fleet plumbing."""

    def __init__(self, inner, checkpoints=None):
        super().__init__(inner)
        self.checkpoints = checkpoints or []

    def step(self):
        self.inner_opt.step()


class AMPOptimizer(_MetaBase):
    """Mixed-precision wrapper (reference: AMPOptimizer — verify):
    fp16 uses GradScaler loss scaling; bf16 (the TPU default) needs
    none, matching `amp.decorate(level="O2")` semantics."""

    def __init__(self, inner, dtype="bfloat16", init_loss_scaling=2.**15):
        super().__init__(inner)
        self.dtype = dtype
        self._scaler = None
        if dtype == "float16":
            from ... import amp
            self._scaler = amp.GradScaler(
                init_loss_scaling=init_loss_scaling)

    def scale_loss(self, loss):
        if self._scaler is not None:
            return self._scaler.scale(loss)
        return loss

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """fp16: the loss MUST be scaled before backward or step()'s
        unscale_ divides never-scaled grads by the loss scale (a
        silent 2^15 lr shrink)."""
        from ... import framework
        if framework.in_static_mode():
            return self.inner_opt.minimize(
                loss, startup_program=startup_program,
                parameters=parameters, no_grad_set=no_grad_set)
        self.scale_loss(loss).backward()
        self.step()
        return None, None

    def step(self):
        if self._scaler is not None:
            self._scaler.step(self.inner_opt)
            self._scaler.update()
            return
        self.inner_opt.step()
