"""Megatron-style TP/SP layers + pipeline scaffolding.

Reference parity: python/paddle/distributed/fleet/layers/mpu/mp_layers.py
(ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
ParallelCrossEntropy), fleet/meta_parallel/ (PipelineLayer, LayerDesc)
— verify.

TPU-native design: TP layers are the SAME math as their serial versions
plus parameter partition specs over the "mp" axis and sharding constraints
at the boundaries — GSPMD inserts the identity-fwd/allreduce-bwd pair the
reference implements as custom ops (mp_ops.py c_identity/c_allreduce).
Sequence parallelism is a constraint over "sep" on the sequence dim."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ... import framework
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer import Layer
from ...param_attr import ParamAttr
from ...tensor import Tensor, apply_op

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy", "LayerDesc",
           "SharedLayerDesc", "PipelineLayer", "ScatterOp", "GatherOp",
           "mark_as_sequence_parallel_parameter", "get_rng_state_tracker"]


def _constrain(x, spec: P):
    """with_sharding_constraint against the active mesh; no-op when no mesh
    is set or an axis in the spec isn't on the mesh."""
    from ..mesh import get_current_mesh
    if not framework.in_functional_mode():
        return x
    mesh = get_current_mesh()
    if mesh is None:
        # fall back to an ambient `with mesh:` context if one is active
        def g(v):
            try:
                return jax.lax.with_sharding_constraint(v, spec)
            except Exception:
                return v
        return apply_op(g, x)
    axes = set(mesh.axis_names)
    for s in spec:
        for a in (s if isinstance(s, tuple) else (s,)):
            if a is not None and a not in axes:
                return x

    def f(v):
        from jax.sharding import NamedSharding
        return jax.lax.with_sharding_constraint(v, NamedSharding(mesh, spec))
    return apply_op(f, x)


class ColumnParallelLinear(Layer):
    """W: (in, out) sharded over "mp" on the OUT dim (reference:
    mp_layers.py ColumnParallelLinear — verify)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features),
            attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.XavierNormal())
        self.weight._sharding_spec = P(None, "mp")
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias._sharding_spec = P("mp")
            self.bias.is_distributed = True
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = _constrain(out, P(*([None] * out.ndim)))
        else:
            out = _constrain(out, P(*([None] * (out.ndim - 1) + ["mp"])))
        return out


class RowParallelLinear(Layer):
    """W: (in, out) sharded over "mp" on the IN dim; partial outputs are
    all-reduced by GSPMD when the constraint demands replication."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features),
            attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.XavierNormal())
        self.weight._sharding_spec = P("mp", None)
        self.weight.is_distributed = True
        self.bias = self.create_parameter(
            (out_features,), is_bias=True) if has_bias else None

    def forward(self, x):
        if self.input_is_parallel:
            x = _constrain(x, P(*([None] * (x.ndim - 1) + ["mp"])))
        out = F.linear(x, self.weight, None)
        out = _constrain(out, P(*([None] * out.ndim)))  # forces all-reduce
        if self.bias is not None:
            out = out + self.bias
        return out


class VocabParallelEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim),
            attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.Normal(0.0, 1.0))
        self.weight._sharding_spec = P("mp", None)
        self.weight.is_distributed = True

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return _constrain(out, P(*([None] * out.ndim)))


class ParallelCrossEntropy(Layer):
    """Vocab-parallel CE: with logits sharded over "mp" on the class dim,
    GSPMD turns log_softmax's reductions into mp all-reduces — the manual
    max/sum allreduce pair of the reference comes for free."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


# ---------------------------------------------------------------------------
# sequence parallel utils (reference: fleet/utils/sequence_parallel_utils.py)
# ---------------------------------------------------------------------------

class ScatterOp:
    """Split activations along seq dim over the mp axis (Megatron-SP)."""

    @staticmethod
    def apply(x, axis=1):
        spec = [None] * x.ndim
        spec[axis] = "mp"
        return _constrain(x, P(*spec))


class GatherOp:
    @staticmethod
    def apply(x, axis=1):
        return _constrain(x, P(*([None] * x.ndim)))


def mark_as_sequence_parallel_parameter(param):
    param.sequence_parallel = True


class _RNGStateTracker:
    """TP-aware rng tracker (reference: fleet/layers/mpu/random.py
    get_rng_state_tracker — verify). With threaded JAX keys, per-region
    determinism is already per-mesh-position; we keep named seeds."""

    def __init__(self):
        self._states = {}

    def add(self, name, seed):
        self._states[name] = jax.random.PRNGKey(seed)

    def rng_state(self, name="model-parallel-rng"):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            if name in self._states:
                with framework.rng_context(self._states[name]):
                    yield
            else:
                yield
        return ctx()


_RNG_TRACKER = _RNGStateTracker()


def get_rng_state_tracker():
    return _RNG_TRACKER


# ---------------------------------------------------------------------------
# pipeline scaffolding
# ---------------------------------------------------------------------------

class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Stage-partitioned sequential model (reference:
    meta_parallel/parallel_layers/pp_layers.py — verify).

    TPU-native execution: all stages live in ONE program; each segment's
    parameters carry a stage tag, and the pipelined schedule (1F1B as a
    lax.scan over microbatches with ppermute between stage-sharded
    segments) is applied by paddle_tpu.distributed.pipeline.
    First-cut forward (no pp axis / pp=1) runs segments sequentially."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 **kwargs):
        super().__init__()
        from ...nn.common import LayerList
        self._descs = list(layers)
        self.loss_fn = loss_fn
        self._num_stages = num_stages or 1
        built = []
        for d in self._descs:
            if isinstance(d, LayerDesc):
                built.append(d.build_layer())
            else:
                built.append(d)
        self.run_function = LayerList(built)
        # stage assignment: uniform split
        n = len(built)
        per = max(1, n // self._num_stages)
        self._stage_of = [min(i // per, self._num_stages - 1)
                          for i in range(n)]
        for i, l in enumerate(built):
            if isinstance(l, Layer):
                for p in l.parameters():
                    p.pp_stage = self._stage_of[i]

    def get_stage_from_index(self, idx):
        return self._stage_of[idx]

    def forward(self, x):
        for fn in self.run_function:
            x = fn(x)
        return x
