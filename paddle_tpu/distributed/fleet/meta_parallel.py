"""Megatron-style TP/SP layers + pipeline scaffolding.

Reference parity: python/paddle/distributed/fleet/layers/mpu/mp_layers.py
(ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
ParallelCrossEntropy), fleet/meta_parallel/ (PipelineLayer, LayerDesc)
— verify.

TPU-native design: TP layers are the SAME math as their serial versions
plus parameter partition specs over the "mp" axis and sharding constraints
at the boundaries — GSPMD inserts the identity-fwd/allreduce-bwd pair the
reference implements as custom ops (mp_ops.py c_identity/c_allreduce).
Sequence parallelism is a constraint over "sep" on the sequence dim."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ... import framework
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer import Layer
from ...param_attr import ParamAttr
from ...tensor import Tensor, apply_op

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy", "LayerDesc",
           "SharedLayerDesc", "PipelineLayer", "ScatterOp", "GatherOp",
           "mark_as_sequence_parallel_parameter", "get_rng_state_tracker"]


def _constrain(x, spec: P):
    """with_sharding_constraint against the active mesh; no-op when no mesh
    is set or an axis in the spec isn't on the mesh."""
    from ..mesh import get_current_mesh
    if not framework.in_functional_mode():
        return x
    mesh = get_current_mesh()
    if mesh is None:
        # fall back to an ambient `with mesh:` context if one is active
        def g(v):
            try:
                return jax.lax.with_sharding_constraint(v, spec)
            except Exception:
                return v
        return apply_op(g, x)
    axes = set(mesh.axis_names)
    for s in spec:
        for a in (s if isinstance(s, tuple) else (s,)):
            if a is not None and a not in axes:
                return x

    def f(v):
        from jax.sharding import NamedSharding
        return jax.lax.with_sharding_constraint(v, NamedSharding(mesh, spec))
    return apply_op(f, x)


class ColumnParallelLinear(Layer):
    """W: (in, out) sharded over "mp" on the OUT dim (reference:
    mp_layers.py ColumnParallelLinear — verify)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features),
            attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.XavierNormal())
        self.weight._sharding_spec = P(None, "mp")
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias._sharding_spec = P("mp")
            self.bias.is_distributed = True
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = _constrain(out, P(*([None] * out.ndim)))
        else:
            out = _constrain(out, P(*([None] * (out.ndim - 1) + ["mp"])))
        return out


class RowParallelLinear(Layer):
    """W: (in, out) sharded over "mp" on the IN dim; partial outputs are
    all-reduced by GSPMD when the constraint demands replication."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features),
            attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.XavierNormal())
        self.weight._sharding_spec = P("mp", None)
        self.weight.is_distributed = True
        self.bias = self.create_parameter(
            (out_features,), is_bias=True) if has_bias else None

    def forward(self, x):
        if self.input_is_parallel:
            x = _constrain(x, P(*([None] * (x.ndim - 1) + ["mp"])))
        out = F.linear(x, self.weight, None)
        out = _constrain(out, P(*([None] * out.ndim)))  # forces all-reduce
        if self.bias is not None:
            out = out + self.bias
        return out


class VocabParallelEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim),
            attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.Normal(0.0, 1.0))
        self.weight._sharding_spec = P("mp", None)
        self.weight.is_distributed = True

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return _constrain(out, P(*([None] * out.ndim)))


class ParallelCrossEntropy(Layer):
    """Vocab-parallel CE: with logits sharded over "mp" on the class dim,
    GSPMD turns log_softmax's reductions into mp all-reduces — the manual
    max/sum allreduce pair of the reference comes for free."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


# ---------------------------------------------------------------------------
# sequence parallel utils (reference: fleet/utils/sequence_parallel_utils.py)
# ---------------------------------------------------------------------------

class ScatterOp:
    """Split activations along seq dim over the mp axis (Megatron-SP)."""

    @staticmethod
    def apply(x, axis=1):
        spec = [None] * x.ndim
        spec[axis] = "mp"
        return _constrain(x, P(*spec))


class GatherOp:
    @staticmethod
    def apply(x, axis=1):
        return _constrain(x, P(*([None] * x.ndim)))


def mark_as_sequence_parallel_parameter(param):
    param.sequence_parallel = True


class _RNGStateTracker:
    """TP-aware rng tracker (reference: fleet/layers/mpu/random.py
    get_rng_state_tracker — verify). With threaded JAX keys, per-region
    determinism is already per-mesh-position; we keep named seeds."""

    def __init__(self):
        self._states = {}

    def add(self, name, seed):
        self._states[name] = jax.random.PRNGKey(seed)

    def rng_state(self, name="model-parallel-rng"):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            if name in self._states:
                with framework.rng_context(self._states[name]):
                    yield
            else:
                yield
        return ctx()


_RNG_TRACKER = _RNGStateTracker()


def get_rng_state_tracker():
    return _RNG_TRACKER


# ---------------------------------------------------------------------------
# pipeline scaffolding
# ---------------------------------------------------------------------------

class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class _SharedCall(Layer):
    """A SharedLayerDesc call site: weight sharing is free in a single
    program — every site reads the same Parameters. The first occurrence
    ``owns`` (registers) the shared instance; later sites keep only an
    unregistered reference. ``forward_func(shared_layer, x)`` is applied
    at EVERY site that declared one (reference pp_layers.py wraps each
    occurrence in partial(forward_func, layer))."""

    def __init__(self, shared_layer, forward_func=None, owns=False):
        super().__init__()
        if owns:
            self.shared = shared_layer  # registered: owns the params
        object.__setattr__(self, "_shared", shared_layer)
        object.__setattr__(self, "_forward_func", forward_func)

    def forward(self, x):
        if self._forward_func is not None:
            return self._forward_func(self._shared, x)
        return self._shared(x)


def _layer_signature(layer):
    """Structural identity used to find the pipelinable trunk: two layers
    with equal signatures can share one compiled stage body. Includes
    scalar config attrs (so Dropout(0.1) != Dropout(0.5)) and the bare
    callable's name (so F.relu != F.gelu)."""
    if not isinstance(layer, Layer):
        return (getattr(layer, "__name__", type(layer).__name__),
                None, None, 0)
    params = tuple((n, tuple(p.shape), str(p._value.dtype))
                   for n, p in layer.named_parameters())
    config = tuple(sorted(
        (k, v) for k, v in vars(layer).items()
        if isinstance(v, (int, float, str, bool, type(None)))
        and not k.startswith("_")))
    bufs = tuple((n, tuple(b.shape)) for n, b in layer.named_buffers())
    return (type(layer).__name__, params, config, bufs)


def _find_periodic_trunk(sigs, min_units):
    """Longest contiguous periodic region of ``sigs``: returns
    (start, period, n_units) maximizing covered length (ties: more
    units). Returns n_units=0 when no region has >= min_units units."""
    n = len(sigs)
    best = (0, 1, 0)  # start, period, units
    for q in range(1, n // 2 + 1):
        i = 0
        while i + q <= n:
            k = 1
            while (i + (k + 1) * q <= n
                   and sigs[i + k * q:i + (k + 1) * q] == sigs[i:i + q]):
                k += 1
            if k >= 2:
                cov, best_cov = k * q, best[2] * best[1]
                if cov > best_cov or (cov == best_cov and k > best[2]):
                    best = (i, q, k)
                i += k * q
            else:
                i += 1
    return best if best[2] >= min_units else (0, 1, 0)


class PipelineLayer(Layer):
    """Stage-partitioned sequential model (reference:
    meta_parallel/parallel_layers/pp_layers.py PipelineLayer — verify).

    TPU-native execution (SURVEY §7 hard part #2): instead of the
    reference's per-stage processes exchanging activations over NCCL p2p,
    all stages live in ONE XLA program. At build time the layer list is
    scanned for its maximal periodic trunk (repeated structurally
    identical units — e.g. transformer blocks, possibly multi-layer
    units like [Linear, ReLU]); the trunk's parameters are stacked into
    (S, U, ...) Parameters sharded over the "pp" mesh axis and executed
    through :func:`paddle_tpu.distributed.pipeline.pipeline_spmd`
    (microbatch scan + ppermute ring). Layers before/after the trunk run
    replicated as prologue/epilogue (embedding/head — cheap relative to
    the trunk, and GSPMD still shards their math over dp/mp).

    When no pp mesh axis is active, the same stacked parameters run as a
    plain lax.scan over units, so the two modes share weights and
    numerics exactly.
    """

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 num_microbatches=None, num_virtual_pipeline_stages=1,
                 **kwargs):
        super().__init__()
        from ...nn.common import LayerList
        self._descs = list(layers)
        self.loss_fn = loss_fn
        self._num_stages = num_stages or 1
        self.num_microbatches = num_microbatches
        self._vpp = max(1, int(num_virtual_pipeline_stages or 1))
        self._recompute = bool(recompute_interval)
        built, shared = [], {}
        for d in self._descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in shared:
                    built.append(_SharedCall(shared[d.layer_name],
                                             d.forward_func))
                else:
                    inst = d.build_layer()
                    shared[d.layer_name] = inst
                    built.append(
                        _SharedCall(inst, d.forward_func, owns=True)
                        if d.forward_func is not None else inst)
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            else:
                built.append(d)
        # stage assignment (uniform split — reference seg_method default)
        n = len(built)
        per = max(1, n // self._num_stages)
        self._stage_of = [min(i // per, self._num_stages - 1)
                          for i in range(n)]
        for i, l in enumerate(built):
            if isinstance(l, Layer):
                for p in l.parameters():
                    p.pp_stage = self._stage_of[i]

        self._pipelined = False
        if self._num_stages > 1:
            self._try_build_trunk(built)
        if not self._pipelined:
            if self._num_stages > 1:
                import warnings
                warnings.warn(
                    "PipelineLayer: no periodic trunk of >= "
                    f"{self._num_stages} structurally identical units "
                    "found; falling back to sequential (un-pipelined) "
                    "execution. Stack identical blocks (LayerDesc of the "
                    "same class/shape) to enable the scan+ppermute "
                    "pipeline.", stacklevel=3)
            self.run_function = LayerList(built)

    # -- trunk construction -------------------------------------------------
    def _try_build_trunk(self, built):
        from ...nn.common import LayerList
        from ...tensor import Parameter
        S = self._num_stages
        V = self._vpp
        sigs = [_layer_signature(l) for l in built]
        start, q, k = _find_periodic_trunk(sigs, S)
        k_used = (k // (S * V)) * (S * V)
        if k_used < max(S * V, 2):
            return
        end = start + k_used * q
        protos = built[start:start + q]
        # buffers can't ride the stacked-substitution path (only params
        # are swapped in _unit_fwd); a trunk with per-unit buffer state
        # (e.g. BatchNorm running stats) must not be silently broken
        for lay in built[start:end]:
            if isinstance(lay, Layer) and lay.buffers():
                return
        # stack unit parameters: leaf (S, U, *shape), pp shards dim 0
        unit_pmaps = [dict(built[start + u * q + j].named_parameters())
                      if isinstance(built[start + u * q + j], Layer)
                      else None
                      for u in range(k_used) for j in range(q)]
        pindex, handles, stacked = [], [], []
        for j in range(q):
            if not isinstance(protos[j], Layer):
                continue
            pmap = dict(protos[j].named_parameters())
            for pname, proto_p in pmap.items():
                vals = []
                for u in range(k_used):
                    vals.append(unit_pmaps[u * q + j][pname]._value)
                if V > 1:
                    # interleaved: device s owns chunks {s, S+s, ...} →
                    # leaf[s, v] = global chunk v·S + s, U units each
                    U = k_used // (S * V)
                    leaf = jnp.stack(vals).reshape(
                        V, S, U, *vals[0].shape).swapaxes(0, 1)
                else:
                    leaf = jnp.stack(vals).reshape(
                        S, k_used // S, *vals[0].shape)
                reg = f"trunk_{j}__{pname.replace('.', '__')}"
                param = Parameter(leaf)
                base = getattr(proto_p, "_sharding_spec", None)
                vpp_none = (None,) * (1 if V > 1 else 0)
                param._sharding_spec = (
                    P("pp", *vpp_none, None, *tuple(base))
                    if base is not None else P("pp"))
                param.is_distributed = True
                self.add_parameter(reg, param)
                pindex.append((j, pname, reg))
                handles.append(proto_p)
                stacked.append(reg)
        if not stacked:
            return
        self.prologue = LayerList(built[:start])
        self.epilogue = LayerList(built[end:])
        object.__setattr__(self, "_protos", protos)
        self._pindex = pindex
        object.__setattr__(self, "_phandles", handles)
        self._period = q
        self._units = k_used
        self._pipelined = True

    def get_stage_from_index(self, idx):
        return self._stage_of[idx]

    # -- execution ----------------------------------------------------------
    def _unit_fwd(self, slices, hv):
        """Run one trunk unit with its parameter values substituted into
        the prototype layers (same trick as models/llama.py
        LlamaDecoderStack._layer_fwd)."""
        saved = [(t, t._value) for t in self._phandles]
        try:
            for t, v in zip(self._phandles, slices):
                t._value = v
            h = Tensor(hv)
            with framework.functional_mode():
                for proto in self._protos:
                    h = proto(h)
            return h._value
        finally:
            for t, v in saved:
                t._value = v

    def _pure_trunk(self, xv, *leafvals):
        from ..mesh import get_current_mesh
        from ..pipeline import (merge_microbatches, num_pipeline_stages,
                                pipeline_spmd, pipeline_spmd_interleaved,
                                split_microbatches)
        mesh = get_current_mesh()
        S_mesh = num_pipeline_stages(mesh)
        S = self._num_stages
        V = self._vpp

        if S_mesh == 1:
            # no pp axis: same stacked weights, plain scan over all
            # units in GLOBAL order (V>1 leaves are (S, V, U, ...) with
            # global chunk v·S+s → transpose back to (V, S, U, ...))
            flat = tuple(
                (l.swapaxes(0, 1).reshape(self._units, *l.shape[3:])
                 if V > 1 else l.reshape(self._units, *l.shape[2:]))
                for l in leafvals)
            body = jax.checkpoint(self._unit_fwd) if self._recompute \
                else self._unit_fwd
            out, _ = jax.lax.scan(lambda h, sl: (body(sl, h), None),
                                  xv, flat)
            return out
        if S_mesh != S:
            from ...utils.enforce import InvalidArgumentError
            raise InvalidArgumentError(
                f"PipelineLayer was built with num_stages={S} but the "
                f"active mesh has pp={S_mesh}",
                "re-build the model or the mesh so the degrees agree")

        def stage_fn(local, h):
            out, _ = jax.lax.scan(lambda hh, sl: (self._unit_fwd(sl, hh),
                                                  None), h, local)
            return out
        M = self.num_microbatches or S
        x_mb = split_microbatches(xv, M)
        if V > 1:
            if x_mb.shape[0] % S != 0:
                raise ValueError(
                    f"interleaved pipeline (V={V}) needs the microbatch "
                    f"count ({x_mb.shape[0]}) divisible by the pp degree "
                    f"({S}); set num_microbatches to a multiple of {S}")
            y_mb = pipeline_spmd_interleaved(
                stage_fn, tuple(leafvals), x_mb, mesh=mesh,
                remat=self._recompute)
        else:
            y_mb = pipeline_spmd(stage_fn, tuple(leafvals), x_mb,
                                 mesh=mesh, remat=self._recompute)
        return merge_microbatches(y_mb)

    def forward(self, x):
        if not self._pipelined:
            for fn in self.run_function:
                x = fn(x)
            return x
        for fn in self.prologue:
            x = fn(x)
        leaves = [self._parameters[reg] for _, _, reg in self._pindex]
        x = apply_op(self._pure_trunk, x, *leaves)
        for fn in self.epilogue:
            x = fn(x)
        return x
