"""Hierarchical + quantized collective communication.

The gradient/activation sync path is the multi-chip hot path, and flat
``lax.p*`` collectives leave two kinds of performance on the table:

- **Topology**: a v5p pod is not a flat ring — the inner mesh axes ride
  3D-torus ICI while the outer axes may cross DCN. HiCCL
  (arXiv:2408.05962) composes big collectives from per-level primitives:
  reduce-scatter inside the fast level, a small all-reduce across the
  slow level, all-gather back. :mod:`.hierarchical` implements that
  decomposition over any two (groups of) mesh axes, chosen automatically
  from the current :mod:`..mesh` topology, with a flat fallback — and
  bit-identical results for exactly-representable sums.
- **Bytes**: gradients tolerate low-precision transport. EQuARX
  (arXiv:2506.17615) shows an in-XLA int8 all-reduce with per-block
  scales and full-precision accumulation at ~2x wire bandwidth.
  :mod:`.quantized` is the same scheme over shard_map: int8 payload,
  fp32 per-bucket scales, fp32 accumulate, documented error bound
  (exact for constant buckets).

On top sits a bucketing scheduler (:mod:`.bucketing`): gradient tensors
coalesce into size-targeted buckets so one collective moves many small
tensors — fewer dispatches, and XLA's latency-hiding scheduler can
overlap bucket k's collective with bucket k+1's math. Off by default;
enable via :func:`configure` or ``PT_COLLECTIVES_BUCKETED_SYNC=1``.

Everything here is **in-graph**: the ``*_collective`` primitives run
inside ``shard_map`` where mesh axis names are bound; the module-level
``all_reduce``/``all_gather``/``reduce_scatter`` wrap them over a mesh
for host-level use (tests, microbench, eager loops). The eager
control-plane API in :mod:`..communication` is unchanged and unrelated.
"""
from __future__ import annotations

import contextlib
import dataclasses
import os
from typing import Optional

__all__ = [
    "CollectiveConfig", "collective_config", "set_collective_config",
    "configure",
    "HierarchyPlan", "plan_hierarchy",
    "hier_all_reduce", "hier_all_gather", "hier_reduce_scatter",
    "all_reduce", "all_gather", "reduce_scatter",
    "quantized_all_reduce", "int8_error_bound",
    "build_buckets", "BucketedGradSync", "bucketed_allreduce_gradients",
    "attach_grad_sync",
    "run_comms_bench",
]


from ...utils.flags import env_flag as _env_flag  # noqa: E402
# (shared falsy spellings with PT_FUSION_PASSES — utils.flags.env_flag)


@dataclasses.dataclass
class CollectiveConfig:
    """Knobs for the collectives subsystem.

    - ``hierarchy``: "auto" decomposes over two mesh axes when the
      reduction spans >= 2 axes of degree > 1; "flat" always uses the
      single fused XLA collective.
    - ``compress``: None (fp32 wire) or "int8" (per-bucket-scaled int8
      payload, fp32 accumulate).
    - ``quant_bucket_size``: elements per int8 scale bucket. Smaller
      buckets -> tighter error bound, more scale overhead
      (4/bucket_size extra bytes per element).
    - ``error_bound``: optional max tolerable |quantized - fp32| per
      element. With ``compress="int8"``, the in-graph bucketed
      grad-sync computes the runtime bound per fused bucket and
      selects the fp32 reduction for any bucket that would exceed it
      (both reductions run for budgeted buckets — a hard guarantee,
      not a free one). Other in-graph callers fetch the bound via
      ``quantized_all_reduce(..., return_error_bound=True)``. The
      eager bucketed path always ships fp32 and never reads this.
    - ``bucket_bytes``: coalescing target for the gradient bucketer
      (reference DataParallel's comm_buffer_size is 25 MB).
    - ``bucketed_grad_sync``: master switch for wiring the bucketer
      into DataParallel / group_sharded_parallel / the optimizer's
      functional grad path. Defaults OFF — flipping it changes comm
      scheduling, never values.
    """
    hierarchy: str = "auto"                 # "auto" | "flat"
    compress: Optional[str] = None          # None | "int8"
    quant_bucket_size: int = 512
    error_bound: Optional[float] = None
    bucket_bytes: int = 25 << 20
    bucketed_grad_sync: bool = dataclasses.field(
        default_factory=lambda: _env_flag("PT_COLLECTIVES_BUCKETED_SYNC"))

    def __post_init__(self):
        if self.hierarchy not in ("auto", "flat"):
            raise ValueError(
                f"hierarchy must be 'auto' or 'flat', got "
                f"{self.hierarchy!r}")
        if self.compress not in (None, "int8"):
            raise ValueError(
                f"compress must be None or 'int8', got {self.compress!r}")
        if self.quant_bucket_size < 1:
            raise ValueError("quant_bucket_size must be >= 1")


_CONFIG = CollectiveConfig()


def collective_config() -> CollectiveConfig:
    return _CONFIG


def set_collective_config(cfg: CollectiveConfig) -> CollectiveConfig:
    global _CONFIG
    prev, _CONFIG = _CONFIG, cfg
    return prev


@contextlib.contextmanager
def configure(**kw):
    """Scoped config override: ``with collectives.configure(
    compress="int8", hierarchy="flat"): ...``"""
    prev = set_collective_config(dataclasses.replace(_CONFIG, **kw))
    try:
        yield _CONFIG
    finally:
        set_collective_config(prev)


from .hierarchical import (HierarchyPlan, plan_hierarchy,          # noqa: E402
                           hier_all_reduce, hier_all_gather,
                           hier_reduce_scatter,
                           all_reduce, all_gather, reduce_scatter)
from .quantized import quantized_all_reduce, int8_error_bound      # noqa: E402
from .bucketing import (build_buckets, BucketedGradSync,           # noqa: E402
                        bucketed_allreduce_gradients, attach_grad_sync)
from .microbench import run_comms_bench                            # noqa: E402
