"""Comms microbenchmark: bytes, algorithmic bandwidth, quant error.

Runs each collective over a mesh (2 x n/2 when >= 4 devices, else
flat), timed over a replicated payload, and reports per collective:

- ``bytes_moved``: algorithmic wire bytes per device for a ring
  realization (the standard NCCL-tests accounting): all-reduce
  ``2*(n-1)/n * S``, reduce-scatter / all-gather ``(n-1)/n * S``;
  the int8 all-reduce scales by the compressed element size.
- ``algbw_gbps``: ``bytes_moved / time`` — comparable across
  collectives and devices counts (the "as fast as the hardware
  allows" number to track per round).
- quantized-vs-fp32 ``max_error`` plus the documented ``error_bound``
  it must sit under, and an exactness check on constant input.

On CPU-simulated devices the absolute times are meaningless for ICI
but the stage proves the code path end-to-end and pins the error
contract; on real multi-chip it becomes the comm headline. Wired into
``bench.py`` as the ``comms`` stage.
"""
from __future__ import annotations

import time

import numpy as np

__all__ = ["run_comms_bench"]


def _build_mesh():
    import jax
    from jax.sharding import Mesh
    devs = jax.devices()
    n = len(devs)
    if n >= 4:
        outer = 2
        arr = np.array(devs[: (n // outer) * outer]).reshape(
            outer, n // outer)
        return Mesh(arr, ("dp", "mp"))
    return Mesh(np.array(devs), ("mp",))


def _timeit(fn, *args, iters=3):
    fn(*args)                                     # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    return out, (time.perf_counter() - t0) / iters


def run_comms_bench(size_mb: float = 4.0, iters: int = 3,
                    mesh=None) -> dict:
    """Returns a JSON-able dict for the bench ``comms`` stage."""
    import jax.numpy as jnp
    from . import all_reduce, all_gather, reduce_scatter
    from .hierarchical import plan_hierarchy
    from .quantized import int8_error_bound

    mesh = mesh if mesh is not None else _build_mesh()
    axes = tuple(a for a, s in zip(mesh.axis_names, mesh.devices.shape)
                 if s > 1) or mesh.axis_names
    plan = plan_hierarchy(axes, mesh)
    n = max(plan.total_size, 1)
    elems = max(int(size_mb * 1e6) // 4 // n * n, n)   # divisible by n
    rs = np.random.RandomState(0)
    # integer-valued fp32: hierarchical vs flat sums stay exact, so the
    # quant error measured below is pure quantization, not reassoc
    data = rs.randint(-64, 64, size=(n, elems)).astype(np.float32)
    x = jnp.asarray(data)
    size_bytes = elems * 4

    out = {"devices": n, "axes": list(plan.axes), "mode": plan.mode,
           "payload_mb": round(size_bytes / 1e6, 3)}

    def entry(t, bytes_moved):
        return {"time_ms": round(t * 1e3, 3),
                "bytes_moved": int(bytes_moved),
                "algbw_gbps": round(bytes_moved / max(t, 1e-9) / 1e9,
                                    3)}

    ring = (n - 1) / n * size_bytes
    ar, t = _timeit(lambda v: all_reduce(v, axes, mesh, compress=None),
                    x, iters=iters)
    out["all_reduce"] = entry(t, 2 * ring)
    ref = np.asarray(ar)

    _, t = _timeit(lambda v: reduce_scatter(v, axes, mesh),
                   x, iters=iters)
    out["reduce_scatter"] = entry(t, ring)

    shard = jnp.asarray(data[:, : elems // n])
    _, t = _timeit(lambda v: all_gather(v, axes, mesh), shard,
                   iters=iters)
    # per-device shard s: each device receives (n-1)*s of new bytes
    out["all_gather"] = entry(t, (n - 1) * (elems // n) * 4)

    # quantized A/B. Bytes are charged for the IMPLEMENTED gather-based
    # algorithm, not an idealized quantized ring: per device,
    # flat = (n-1) * S_q (full-payload code gather);
    # hier = (I-1)*S_q  phase-1 inner gather
    #      + 2*(O-1)/O * S/I  fp32 outer all-reduce
    #      + (I-1)*S_q/I  phase-2 inner chunk gather
    # with S_q = S * (1 + 4/bucket)/4. The per-hop compression is
    # 4 -> (1+4/bucket) bytes/elem; end-to-end the win only
    # materializes on the hierarchical path (~1.4x at 2x4).
    from . import collective_config
    bucket = collective_config().quant_bucket_size
    qar, t = _timeit(lambda v: all_reduce(v, axes, mesh,
                                          compress="int8"), x,
                     iters=iters)
    q_per = (1.0 + 4.0 / bucket) / 4.0
    if plan.flat:
        qbytes = (n - 1) * size_bytes * q_per
    else:
        inner = plan.inner_size
        outer = n // inner
        qbytes = ((inner - 1) * size_bytes * q_per
                  + 2 * (outer - 1) / outer * (size_bytes / inner)
                  + (inner - 1) * size_bytes * q_per / inner)
    q = entry(t, qbytes)
    err = float(np.max(np.abs(np.asarray(qar) - ref)))
    bound = float(int8_error_bound(np.abs(data).max(), n,
                                   bucket_absmax_out=np.abs(ref).max()))
    q["max_error"] = err
    q["error_bound"] = bound
    q["within_bound"] = bool(err <= bound)
    # constant input must round-trip exactly
    const = jnp.full((n, 4 * bucket), 3.25, jnp.float32)
    qc = np.asarray(all_reduce(const, axes, mesh, compress="int8"))
    q["constant_exact"] = bool(np.all(qc == 3.25 * n))
    out["all_reduce_int8"] = q
    out["quant_vs_fp32_max_error"] = err
    return out
