"""EQuARX-style int8 quantized all-reduce (arXiv:2506.17615).

Wire format: the fp32 payload is split into fixed-size buckets; each
bucket ships as int8 codes plus one fp32 absmax scale. Accumulation is
always fp32 — codes are dequantized per contribution and summed, never
added in int8 (no overflow, no compounding). Each quantized hop
carries ~(1 + 4/bucket) bytes/element instead of 4 (~3.9x per hop at
the default bucket of 512). NOTE on end-to-end wire totals: the
current realization is gather-based (codes are all-gathered and
reduced at every receiver), not a quantized ring reduce-scatter, so
per-device traffic is (n-1)·S_q per gather — the net win over the
fp32 ring is ~1.4x on the hierarchical path and nil on the flat path
(which exists for the numerics contract). EQuARX's in-XLA ring rewrite
is what unlocks the full per-hop factor; the wire format, error
contract and API here are built for it.

Error contract (documented, tested, and computable at runtime):

    per-bucket quantization step   s = absmax / 127
    per-element contribution error <= s/2            (round-to-nearest)
    n-way reduce, phase 1          <= n * s_in/2
    re-quantized gather, phase 2   <= s_out/2

so |quantized - fp32| <= n * max_bucket_scale_in / 2 + bucket_scale_out
/ 2 elementwise (:func:`int8_error_bound`). A bucket whose elements are
all equal is EXACT: absmax is represented by code +-127 with no
rounding, in both phases. Gradients (zero-mean, bucket-local dynamic
range) sit far inside the bound in practice.

In-graph: call :func:`quantized_all_reduce` inside ``shard_map``; the
host-level ``collectives.all_reduce(..., compress="int8")`` wraps it.
Hierarchical plans quantize the bulk inner phases (reduce-scatter +
all-gather, the full-payload traffic) and keep the small outer
all-reduce fp32 — the EQuARX trade applied to the HiCCL decomposition.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .hierarchical import HierarchyPlan, pad_to_multiple

_QMAX = 127.0


def _quantize(flat, bucket_size):
    """(padded_len,) fp32 -> ((nb, bucket) int8 codes, (nb,) fp32
    per-bucket absmax scales). Padding to a bucket multiple is the
    caller's job. The scale is stored as the RAW absmax (not
    absmax/127): dequant then computes (q/127)*scale, so the extreme
    codes +-127 reproduce +-absmax bit-exactly — fl(127/127) == 1 —
    which is what makes constant buckets round-trip exactly even after
    XLA constant-folds the arithmetic."""
    nb = flat.size // bucket_size
    b = flat.reshape(nb, bucket_size)
    scale = jnp.max(jnp.abs(b), axis=1)                    # (nb,)
    denom = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(b / denom[:, None] * _QMAX), -_QMAX, _QMAX)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def _dequantize(q, scale):
    qf = q.astype(jnp.float32)
    s = scale[..., None]
    # the +-127 codes dequantize as sign*absmax with NO arithmetic on
    # the 1/127 step — XLA rewrites x/127 to x*(1/127) (inexact), which
    # would smear constant buckets by an ulp; interior codes keep the
    # scaled form and stay inside the documented half-step bound
    return jnp.where(jnp.abs(qf) == _QMAX, jnp.sign(qf) * s,
                     qf * (s / _QMAX))


def int8_error_bound(x_absmax, nranks: int, bucket_absmax_out=None):
    """Worst-case |quantized - fp32| for an n-way int8 all-reduce.

    ``x_absmax``: max |input| over the relevant bucket across all
    contributors (scalar or array). ``bucket_absmax_out``: max |reduced
    value| over the bucket — defaults to the loose ``n * x_absmax``.
    Both phases quantized (contribution + gathered result)."""
    x_absmax = jnp.asarray(x_absmax, jnp.float32)
    out_mx = jnp.asarray(bucket_absmax_out, jnp.float32) \
        if bucket_absmax_out is not None else nranks * x_absmax
    return nranks * (x_absmax / _QMAX) / 2 + (out_mx / _QMAX) / 2


def _gather_dequant_sum(flat, axes, bucket_size):
    """Quantized all-reduce core over ``axes``: each device ships
    (codes, scales); every receiver accumulates the dequantized
    contributions in fp32. Returns (reduced flat fp32, per-bucket max
    input scale across contributors — the error-bound term)."""
    q, s = _quantize(flat, bucket_size)
    qg = jax.lax.all_gather(q, axes)          # (n, nb, bucket) int8
    sg = jax.lax.all_gather(s, axes)          # (n, nb) fp32
    acc = jnp.sum(_dequantize(qg, sg), axis=0)
    return acc.reshape(-1), jnp.max(sg, axis=0)


def quantized_all_reduce(x, plan: HierarchyPlan,
                         bucket_size: Optional[int] = None,
                         return_error_bound: bool = False):
    """In-graph int8 all-reduce (sum) over ``plan.axes``.

    fp32/bf16 in, same dtype out; accumulate fp32. With
    ``return_error_bound=True`` also returns the runtime worst-case
    elementwise error (scalar fp32) from the actual bucket scales, so
    callers/benchmarks can check it against a configured budget."""
    from . import collective_config
    if bucket_size is None:
        bucket_size = collective_config().quant_bucket_size
    shape, dtype = x.shape, x.dtype
    with jax.named_scope(f"collectives.quantized_all_reduce[{plan.mode}]"):
        flat = x.reshape(-1).astype(jnp.float32)
        size = flat.size
        if plan.flat:
            flat, _ = pad_to_multiple(flat, bucket_size)
            red, s_in_max = _gather_dequant_sum(flat, plan.axes,
                                                bucket_size)
            # phase 2: the flat path gathers nothing (every device
            # reduced the full payload) — only the contribution error
            # applies, but keep the documented two-phase bound so flat
            # and hierarchical quote the same contract.
            q2, s_out = _quantize(red, bucket_size)
            out = _dequantize(q2, s_out).reshape(-1)
        else:
            # pad so inner chunks split on bucket boundaries: chunk
            # size must be a bucket multiple
            flat, _ = pad_to_multiple(flat, bucket_size * plan.inner_size)
            chunk = flat.size // plan.inner_size
            # phase 1: quantized reduce-scatter within the inner level
            # (bulk traffic) — gather codes, fp32-accumulate, keep own
            # chunk
            red, s_in_max = _gather_dequant_sum(flat, plan.inner,
                                                bucket_size)
            idx = jax.lax.axis_index(plan.inner)
            own = jax.lax.dynamic_slice(red, (idx * chunk,), (chunk,))
            # small fp32 all-reduce across the outer level (1/inner of
            # the payload; crosses the slow links)
            own = jax.lax.psum(own, plan.outer)
            # phase 2: quantized all-gather back within the inner level
            q2, s_out = _quantize(own, bucket_size)
            qg = jax.lax.all_gather(q2, plan.inner)
            sg = jax.lax.all_gather(s_out, plan.inner)
            out = _dequantize(qg, sg).reshape(-1)
            s_out = sg
        out = out[:size].reshape(shape).astype(dtype)
        if not return_error_bound:
            return out
        # scalar bound from the worst bucket of each phase; the phase-1
        # scales of OTHER outer groups are not local, so pmax them in
        s_in = jnp.max(s_in_max)          # scales ARE bucket absmaxes
        if not plan.flat:
            s_in = jax.lax.pmax(s_in, plan.outer)
        # n comes from the BOUND axes, not the plan: a bare shard_map
        # with no registered mesh plans flat with total_size=1, which
        # understated the bound ~n-fold and let BucketedGradSync's
        # error_bound hard-guarantee mode keep over-budget buckets.
        # psum of the literal 1 folds to the static axis-size product
        # at trace time (same idiom as bucketing.BucketedGradSync).
        n = jax.lax.psum(1, plan.axes)
        bound = int8_error_bound(s_in, n,
                                 bucket_absmax_out=jnp.max(s_out))
        return out, bound
