"""HiCCL-style hierarchical collective primitives over mesh axes.

A reduction spanning two mesh levels — e.g. ("dp", "mp") where "mp"
rides intra-slice ICI and "dp" crosses slices/DCN — decomposes
(arXiv:2408.05962):

    all-reduce      = reduce-scatter(inner) ; all-reduce(outer)
                      ; all-gather(inner)
    reduce-scatter  = reduce-scatter(outer) ; reduce-scatter(inner)
    all-gather      = all-gather(inner) ; all-gather(outer)

The inner (fastest-ICI, innermost in mesh.AXIS_ORDER) level carries the
full payload; the outer level only moves 1/inner_size of it. Chunk
ordering is chosen so every composition is **bit-identical** to the
flat single-call collective over the same axes whenever the sums are
exactly representable (always for the data-movement collectives; for
fp32 sums whenever addition does not round, e.g. integer-valued
gradients — otherwise within normal fp32 reassociation noise).

These primitives are IN-GRAPH: call them inside ``shard_map`` where the
axis names are bound. The module-level :func:`all_reduce` /
:func:`all_gather` / :func:`reduce_scatter` wrappers at the bottom run
them over a mesh from host level (stacked per-device contributions in,
global result out) — the form the tests and the comms microbench use.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..mesh import get_current_mesh

Axes = Union[str, Sequence[str]]


def _axis_sizes(mesh: Mesh) -> dict:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def _norm_axes(axes: Axes) -> Tuple[str, ...]:
    return (axes,) if isinstance(axes, str) else tuple(axes)


@dataclasses.dataclass(frozen=True)
class HierarchyPlan:
    """How one logical collective maps onto mesh levels.

    ``axes`` is the full reduction scope (mesh order, outer->inner).
    ``inner`` is the fastest level (one axis name) and ``outer`` the
    remaining axes, or both None for a flat plan. ``inner_size`` /
    ``total_size`` are static device counts used for padding/chunking.
    """
    axes: Tuple[str, ...]
    outer: Optional[Tuple[str, ...]]
    inner: Optional[str]
    inner_size: int
    total_size: int

    @property
    def flat(self) -> bool:
        return self.inner is None

    @property
    def mode(self) -> str:
        return "flat" if self.flat else "hierarchical"


def plan_hierarchy(axes: Axes, mesh: Optional[Mesh] = None,
                   hierarchy: Optional[str] = None) -> HierarchyPlan:
    """Pick the decomposition for a reduction over ``axes``.

    Axes are re-ordered to mesh order (outermost first — matching what
    a flat multi-axis collective does with that tuple). When >= 2 of
    them have degree > 1 and ``hierarchy`` resolves to "auto", the
    innermost becomes the fast level; otherwise the plan is flat.
    Degree-1 axes are dropped (they contribute nothing but would still
    force XLA to emit a wider replica-group table)."""
    from . import collective_config
    if hierarchy is None:
        hierarchy = collective_config().hierarchy
    mesh = mesh if mesh is not None else get_current_mesh()
    names = _norm_axes(axes)
    if mesh is None:                      # no topology known: flat as-is
        return HierarchyPlan(names, None, None, 1, 1)
    sizes = _axis_sizes(mesh)
    for a in names:
        if a not in sizes:
            raise ValueError(
                f"axis {a!r} not in mesh axes {tuple(sizes)}")
    order = {a: i for i, a in enumerate(mesh.axis_names)}
    names = tuple(sorted(dict.fromkeys(names), key=order.__getitem__))
    live = tuple(a for a in names if sizes[a] > 1)
    total = int(np.prod([sizes[a] for a in live])) if live else 1
    if hierarchy != "auto" or len(live) < 2:
        return HierarchyPlan(live or names[-1:], None, None, 1, total)
    return HierarchyPlan(live, live[:-1], live[-1], sizes[live[-1]],
                         total)


# --------------------------------------------------------------------------
# in-graph primitives (call inside shard_map)
# --------------------------------------------------------------------------

def pad_to_multiple(flat, multiple):
    """Zero-pad a 1-D array so ``multiple`` divides it; returns
    (padded, pad). Shared by the hierarchical chunking here and the
    quantization bucketing in :mod:`.quantized`."""
    pad = (-flat.size) % multiple
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat, pad


def hier_all_reduce(x, plan: HierarchyPlan):
    """All-reduce (sum) over ``plan.axes``; hierarchical plans run
    reduce-scatter(inner) -> all-reduce(outer) -> all-gather(inner),
    padding the flattened payload so the inner level divides it."""
    if plan.flat:
        with jax.named_scope("collectives.all_reduce[flat]"):
            return jax.lax.psum(x, plan.axes)
    with jax.named_scope("collectives.all_reduce[hier]"):
        shape, dtype = x.shape, x.dtype
        flat, pad = pad_to_multiple(x.reshape(-1), plan.inner_size)
        part = jax.lax.psum_scatter(flat, plan.inner,
                                    scatter_dimension=0, tiled=True)
        part = jax.lax.psum(part, plan.outer)
        out = jax.lax.all_gather(part, plan.inner, axis=0, tiled=True)
        if pad:
            out = out[:flat.size - pad]
        return out.reshape(shape)


def hier_reduce_scatter(x, plan: HierarchyPlan):
    """Reduce-scatter (sum) over ``plan.axes`` along dim 0 (tiled):
    in (N, ...) per device -> out (N/total, ...), the chunk for this
    device's linear index over ``plan.axes`` (outer-major — identical
    chunk assignment to the flat collective). Hierarchical plans
    scatter outer-first so chunk order is preserved."""
    n = plan.total_size
    if x.shape[0] % max(n, 1):
        raise ValueError(
            f"reduce_scatter dim 0 ({x.shape[0]}) not divisible by "
            f"device count {n} over axes {plan.axes}")
    if plan.flat:
        with jax.named_scope("collectives.reduce_scatter[flat]"):
            return jax.lax.psum_scatter(x, plan.axes,
                                        scatter_dimension=0, tiled=True)
    with jax.named_scope("collectives.reduce_scatter[hier]"):
        out = jax.lax.psum_scatter(x, plan.outer, scatter_dimension=0,
                                   tiled=True)
        return jax.lax.psum_scatter(out, plan.inner,
                                    scatter_dimension=0, tiled=True)


def hier_all_gather(x, plan: HierarchyPlan):
    """All-gather over ``plan.axes`` along dim 0 (tiled): in (M, ...)
    per device -> out (M*total, ...) with shards in linear-index order
    (outer-major). Hierarchical plans gather inner-first, which keeps
    that order while the outer level moves already-widened blocks."""
    if plan.flat:
        with jax.named_scope("collectives.all_gather[flat]"):
            return jax.lax.all_gather(x, plan.axes, axis=0, tiled=True)
    with jax.named_scope("collectives.all_gather[hier]"):
        out = jax.lax.all_gather(x, plan.inner, axis=0, tiled=True)
        return jax.lax.all_gather(out, plan.outer, axis=0, tiled=True)


# --------------------------------------------------------------------------
# host-level wrappers (tests / microbench / eager loops)
# --------------------------------------------------------------------------

def _unwrap(x):
    from ...tensor import Tensor
    if isinstance(x, Tensor):
        return x._value, True
    return jnp.asarray(x), False


def _wrap(v, was_tensor):
    if was_tensor:
        from ...tensor import Tensor
        return Tensor(v)
    return v


def _resolve(axes, mesh, hierarchy):
    mesh = mesh if mesh is not None else get_current_mesh()
    if mesh is None:
        raise ValueError(
            "collectives need a mesh: pass mesh= or build one via "
            "HybridCommunicateGroup / build_device_mesh")
    if axes is None:
        axes = tuple(a for a, s in _axis_sizes(mesh).items() if s > 1)
        if not axes:
            axes = (mesh.axis_names[-1],)
    plan = plan_hierarchy(axes, mesh, hierarchy)
    return mesh, plan


def _record(name):
    from ...profiler import RecordEvent
    return RecordEvent(name)


_om = None        # observability.metrics, imported on first dispatch
                  # (collectives load during package init, before
                  # ``paddle_tpu.utils`` exists, so no top-level import)


def _note_metrics(op: str, plan: HierarchyPlan, v, int8: bool = False):
    """Per-call collective metrics: calls + payload bytes (labelled by
    op and plan mode) and, for the quantized path, the runtime int8
    error bound on this payload. The module is imported once and
    cached; after that the disarmed path is one None test + one bool
    check, and the absmax host sync only happens armed."""
    global _om
    if _om is None:
        from ...observability import metrics as _om
    om = _om
    if not om.enabled():
        return
    mode = plan.mode + (",int8" if int8 else "")
    om.counter("pt_collectives_calls_total",
               "host-level collective dispatches",
               labels=("op", "mode")).inc(op=op, mode=mode)
    om.counter("pt_collectives_bytes_total",
               "payload bytes handed to collectives (stacked "
               "contributions; algorithmic wire bytes are the comms "
               "microbench's job)",
               labels=("op", "mode")).inc(v.nbytes, op=op, mode=mode)
    if int8:
        from .quantized import int8_error_bound
        absmax = float(jnp.max(jnp.abs(v)))
        om.gauge("pt_collectives_int8_error_bound",
                 "worst-case |dequant - fp32| of the most recent int8 "
                 "all-reduce payload").set(
            float(int8_error_bound(absmax, plan.total_size)))


@functools.lru_cache(maxsize=256)
def _compiled(op: str, mesh: Mesh, plan: HierarchyPlan,
              bucket_size: Optional[int]):
    """Jitted shard_map program per (op, mesh, plan) — host-level
    wrappers would otherwise re-trace on every call, which both costs
    milliseconds and makes the microbench time tracing, not comms."""
    from jax.experimental.shard_map import shard_map

    if op == "all_reduce":
        inner = lambda xl: hier_all_reduce(        # noqa: E731
            jnp.squeeze(xl, 0), plan)
        out_specs = P()
    elif op == "all_reduce_int8":
        from .quantized import quantized_all_reduce
        inner = lambda xl: quantized_all_reduce(   # noqa: E731
            jnp.squeeze(xl, 0), plan, bucket_size=bucket_size)
        out_specs = P()
    elif op == "reduce_scatter":
        def inner(xl):
            return hier_reduce_scatter(jnp.squeeze(xl, 0), plan)[None]
        out_specs = P(plan.axes)
    elif op == "all_gather":
        inner = lambda xl: hier_all_gather(        # noqa: E731
            jnp.squeeze(xl, 0), plan)
        out_specs = P()
    else:  # pragma: no cover
        raise ValueError(op)
    return jax.jit(shard_map(inner, mesh=mesh,
                             in_specs=(P(plan.axes),),
                             out_specs=out_specs, check_rep=False))


def all_reduce(x, axes: Optional[Axes] = None, mesh: Optional[Mesh] = None,
               compress: Optional[str] = "__config__",
               hierarchy: Optional[str] = None):
    """Sum stacked per-device contributions.

    ``x``: (n_devices, *shape) — row d is device d's term (linear index
    over ``axes``, outer-major). Returns (*shape), the sum every device
    ends up holding. ``compress="int8"`` routes through the quantized
    wire format (see :mod:`.quantized`); default follows the global
    config."""
    from . import collective_config
    cfg = collective_config()
    if compress == "__config__":
        compress = cfg.compress
    v, wast = _unwrap(x)
    mesh, plan = _resolve(axes, mesh, hierarchy)
    if v.shape[0] != plan.total_size:
        raise ValueError(
            f"all_reduce expects stacked contributions with dim 0 == "
            f"{plan.total_size} (devices over {plan.axes}), got "
            f"{v.shape}")
    op = "all_reduce_int8" if compress == "int8" else "all_reduce"
    # bucket size only shapes the int8 program; keying the fp32 cache
    # on it would recompile identical programs on config churn
    bucket = cfg.quant_bucket_size if compress == "int8" else None
    _note_metrics("all_reduce", plan, v, int8=compress == "int8")
    with _record(f"collectives::all_reduce[{plan.mode}"
                 f"{',int8' if compress == 'int8' else ''}]"):
        out = _compiled(op, mesh, plan, bucket)(v)
        out.block_until_ready()
    return _wrap(out, wast)


def reduce_scatter(x, axes: Optional[Axes] = None,
                   mesh: Optional[Mesh] = None,
                   hierarchy: Optional[str] = None):
    """Reduce-scatter stacked per-device contributions.

    ``x``: (n_devices, N, ...) — row d is device d's full-length term.
    Returns (n_devices, N/n, ...): row d is the reduced chunk device d
    holds afterwards (so callers can check placement, not just values).
    """
    v, wast = _unwrap(x)
    mesh, plan = _resolve(axes, mesh, hierarchy)
    n = plan.total_size
    if v.shape[0] != n:
        raise ValueError(
            f"reduce_scatter expects dim 0 == {n}, got {v.shape}")
    _note_metrics("reduce_scatter", plan, v)
    with _record(f"collectives::reduce_scatter[{plan.mode}]"):
        out = _compiled("reduce_scatter", mesh, plan, None)(v)
        out.block_until_ready()
    return _wrap(out, wast)


def all_gather(x, axes: Optional[Axes] = None, mesh: Optional[Mesh] = None,
               hierarchy: Optional[str] = None):
    """All-gather stacked per-device shards.

    ``x``: (n_devices, M, ...) — row d is device d's shard. Returns
    (n_devices * M, ...), the concatenation (linear order over
    ``axes``) every device ends up holding."""
    v, wast = _unwrap(x)
    mesh, plan = _resolve(axes, mesh, hierarchy)
    if v.shape[0] != plan.total_size:
        raise ValueError(
            f"all_gather expects dim 0 == {plan.total_size}, got "
            f"{v.shape}")
    _note_metrics("all_gather", plan, v)
    with _record(f"collectives::all_gather[{plan.mode}]"):
        out = _compiled("all_gather", mesh, plan, None)(v)
        out.block_until_ready()
    return _wrap(out, wast)
