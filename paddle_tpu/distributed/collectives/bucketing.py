"""Gradient bucketing: coalesce many small syncs into few big ones.

The reference's C++ Reducer concatenates gradients into ~25 MB fusion
buffers and all-reduces buffer-at-a-time so communication of bucket k
overlaps the backward math producing bucket k+1. On TPU the same shape
pays off twice: one collective per bucket instead of per tensor (XLA
dispatch + replica-group setup amortized), and the buckets give XLA's
latency-hiding scheduler clean units to overlap.

Two consumers:

- :class:`BucketedGradSync` — IN-GRAPH hook for the optimizer's
  functional update (``optimizer._grad_sync``). Inside ``shard_map``
  it buckets, runs one (hierarchical/quantized, per config) all-reduce
  per bucket, means, and splits back. Anywhere the axes are not bound
  (plain GSPMD jit, eager) it is an exact no-op — GSPMD already owns
  the sync there, so attaching the hook can never double-reduce.
- :func:`bucketed_allreduce_gradients` — EAGER drop-in used by
  ``fleet.utils.fused_allreduce_gradients``: one store/multihost
  all-reduce per bucket instead of per parameter. On the TCPStore
  control-plane transport that collapses O(params) rendezvous rounds
  into O(buckets).

With compress off and fp32 gradients, both preserve values exactly vs
the unbucketed path: same summands, same per-element reduction —
concatenation never reassociates a single element's sum. (bf16 grads
are upcast to fp32 for the fused wire, i.e. at least as accurate;
``compress="int8"`` trades the documented quantization error.)

Everything is OFF by default: wire-up happens only when
``CollectiveConfig.bucketed_grad_sync`` is set (or
``PT_COLLECTIVES_BUCKETED_SYNC=1``).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["build_buckets", "BucketedGradSync",
           "bucketed_allreduce_gradients", "attach_grad_sync"]


def build_buckets(named_sizes: Sequence[Tuple[str, int]],
                  bucket_bytes: int = 25 << 20,
                  elem_bytes: int = 4) -> List[List[str]]:
    """Greedy size-targeted bucketing, order-preserving.

    ``named_sizes``: (name, element_count) in sync order (reverse
    creation order approximates backward completion order, as in the
    reference Reducer). A tensor larger than the target gets its own
    bucket; buckets are never empty."""
    target = max(int(bucket_bytes), 1)
    buckets: List[List[str]] = []
    cur: List[str] = []
    cur_bytes = 0
    for name, n in named_sizes:
        nbytes = int(n) * elem_bytes
        if cur and cur_bytes + nbytes > target:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(name)
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets


def _axes_bound(axes) -> bool:
    """True iff EVERY axis name is bound in the current trace (i.e. we
    are inside shard_map over them); False iff NONE is. A partial
    binding raises: syncing over a subset the caller didn't get —
    or silently skipping the sync — would both train replicas apart.
    Probing is trace-time-deterministic so the try/except bakes no
    data dependence into the jaxpr."""
    names = tuple(axes) if isinstance(axes, (tuple, list)) else (axes,)
    bound = []
    for a in names:
        try:
            jax.lax.axis_index(a)
            bound.append(a)
        except NameError:
            pass
    if bound and len(bound) != len(names):
        raise ValueError(
            f"BucketedGradSync over axes {names}: only {tuple(bound)} "
            f"are bound in this shard_map — attach the hook with the "
            f"axes the step actually maps over")
    return bool(bound)


class BucketedGradSync:
    """In-graph gradient sync: mean-all-reduce over mesh ``axes`` in
    size-targeted buckets. Attach via :func:`attach_grad_sync`; the
    optimizer calls it as ``grads = hook(grads)`` at the top of
    ``functional_update`` (before clipping, matching DDP semantics)."""

    def __init__(self, axes=("dp",), bucket_bytes: Optional[int] = None,
                 compress: Optional[str] = "__config__",
                 hierarchy: Optional[str] = None,
                 mesh=None):
        from . import collective_config
        cfg = collective_config()
        self.axes = tuple(axes) if isinstance(axes, (tuple, list)) \
            else (axes,)
        self.bucket_bytes = int(bucket_bytes if bucket_bytes is not None
                                else cfg.bucket_bytes)
        self.compress = cfg.compress if compress == "__config__" \
            else compress
        self.error_bound = cfg.error_bound
        self.hierarchy = hierarchy
        self.mesh = mesh

    def _plan(self):
        from .hierarchical import plan_hierarchy
        return plan_hierarchy(self.axes, self.mesh, self.hierarchy)

    def __call__(self, grads: Dict[str, jnp.ndarray]) -> Dict:
        if not grads or not _axes_bound(self.axes):
            return grads          # GSPMD/eager: sync is not ours to do
        from ...profiler import RecordEvent
        from .hierarchical import hier_all_reduce
        from .quantized import quantized_all_reduce
        plan = self._plan()
        # the mean divisor comes from the BOUND axes, not the plan: a
        # shard_map step without a registered mesh would plan flat with
        # total_size=1 and silently turn mean into sum. psum of the
        # literal 1 folds to the static axis-size product at trace time.
        n = jax.lax.psum(1, tuple(self.axes))
        names = [k for k, g in grads.items()
                 if g is not None and int(np.prod(g.shape)) > 0]
        sizes = [(k, int(np.prod(grads[k].shape))) for k in names]
        out = dict(grads)
        with RecordEvent(f"collectives::grad_sync[{plan.mode}"
                         f"{',int8' if self.compress == 'int8' else ''}"
                         f",buckets]"):
            for bucket in build_buckets(sizes, self.bucket_bytes):
                with jax.named_scope("collectives.grad_bucket"):
                    flats = [grads[k].reshape(-1).astype(jnp.float32)
                             for k in bucket]
                    fused = flats[0] if len(flats) == 1 \
                        else jnp.concatenate(flats)
                    if self.compress == "int8":
                        if self.error_bound is not None:
                            # budgeted mode: compute the quantized
                            # result AND its runtime bound, fall back
                            # to the fp32 reduction for any bucket
                            # whose bound exceeds the budget (costs
                            # both reductions for that bucket — the
                            # price of a hard guarantee)
                            q, b = quantized_all_reduce(
                                fused, plan, return_error_bound=True)
                            f = hier_all_reduce(fused, plan)
                            fused = jnp.where(b <= self.error_bound,
                                              q, f)
                        else:
                            fused = quantized_all_reduce(fused, plan)
                    else:
                        fused = hier_all_reduce(fused, plan)
                    fused = fused / n
                    off = 0
                    for k in bucket:
                        g = grads[k]
                        sz = int(np.prod(g.shape))
                        out[k] = jax.lax.dynamic_slice(
                            fused, (off,), (sz,)).reshape(g.shape) \
                            .astype(g.dtype)
                        off += sz
        return out


def attach_grad_sync(optimizer, axes=("dp",), **kw):
    """Install a :class:`BucketedGradSync` as the optimizer's functional
    grad hook. Returns the hook (or None when the config flag is off
    and ``force`` was not passed)."""
    force = kw.pop("force", False)
    from . import collective_config
    if not (force or collective_config().bucketed_grad_sync):
        # flag off: also clear stale wiring from an earlier flag-on
        # call (re-sharding must not keep syncing over the old axis);
        # a user's custom non-bucketed hook is left alone
        if isinstance(getattr(optimizer, "_grad_sync", None),
                      BucketedGradSync):
            optimizer._grad_sync = None
        return None
    hook = BucketedGradSync(axes=axes, **kw)
    optimizer._grad_sync = hook
    return hook


def bucketed_allreduce_gradients(parameter_list, group=None,
                                 bucket_bytes: Optional[int] = None):
    """Eager bucketed mean-all-reduce of ``p.grad`` across the data-
    parallel group — the coalesced form of fleet's
    ``fused_allreduce_gradients``. One flattened fp32 all_reduce per
    size-targeted bucket; values bit-match the per-tensor path."""
    from ...tensor import Tensor
    from .. import communication as C
    from ...profiler import RecordEvent
    from . import collective_config

    if bucket_bytes is None:
        bucket_bytes = collective_config().bucket_bytes
    params = [p for p in parameter_list
              if isinstance(p, Tensor) and p.grad is not None
              and int(np.prod(p.grad.shape)) > 0]
    if not params:
        return
    if group is not None and getattr(group, "nranks", 0):
        n = group.nranks
    else:
        from ..parallel import ParallelEnv
        n = max(ParallelEnv().world_size, 1)
    sizes = [(i, int(np.prod(p.grad.shape)))
             for i, p in enumerate(params)]
    with RecordEvent("collectives::grad_sync[eager,buckets]"):
        for bucket in build_buckets(sizes, bucket_bytes):
            grads = [params[i].grad for i in bucket]
            fused = Tensor(jnp.concatenate(
                [g._value.reshape(-1).astype(jnp.float32)
                 for g in grads]))
            C.all_reduce(fused, op=C.ReduceOp.SUM, group=group)
            flat = fused._value / n if n > 1 else fused._value
            off = 0
            for i in bucket:
                g = params[i].grad
                sz = int(np.prod(g.shape))
                g._update_value(
                    flat[off:off + sz].reshape(g.shape)
                    .astype(g._value.dtype))
                off += sz
