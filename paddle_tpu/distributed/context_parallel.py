"""Context / sequence parallelism for long sequences.

Reference parity: (1) the SEP/Ulysses axis of HybridCommunicateGroup
(python/paddle/distributed/fleet/base/topology.py — verify):
DeepSpeed-Ulysses-style all-to-all swapping seq-sharding for head-sharding
around attention; (2) ring flash attention (ecosystem
PaddleNLP ring_flash_attention.py, enabled by the core flash-attn kernel's
softmax_lse output — SURVEY §2.3 CP row).

TPU-native design (SURVEY §5): the sequence axis is a first-class mesh
dim.  Ring attention = shard_map over the axis with KV blocks rotating via
``ppermute`` over ICI and an online-softmax merge (the softmax_lse the
reference threads between kernel calls is just the (m, l) accumulator pair
here).  Ulysses = two ``all_to_all``s around a plain flash attention.
Both are differentiable (ppermute/all_to_all have transpose rules), so
the backward pass is the reverse ring — no hand-written grad kernels.

Layout convention is paddle's bshd: (batch, seq, num_heads, head_dim).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["ring_attention_spmd", "ulysses_attention_spmd",
           "RingAttention", "sep_degree"]


def sep_degree(mesh: Optional[Mesh], axis: str = "sep") -> int:
    if mesh is None or axis not in mesh.axis_names:
        return 1
    return int(mesh.shape[axis])


def _repeat_kv(q, k, v):
    if k.shape[2] != q.shape[2]:  # GQA: repeat kv heads to match q
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


def _xla_block(q, k, v, causal, scale):
    """(o, lse) of one attention block without Pallas: the grouped-GQA
    einsum fallback for the ring inner step. o (b,sq,h,d), lse (b,h,sq)."""
    b, sq, h, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    g = h // hk
    qg = q.reshape(b, sq, hk, g, d).astype(jnp.float32)
    s = jnp.einsum("bqkgd,bskd->bkgqs", qg, k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask[None, None, None], s, -jnp.inf)
    m = jnp.max(s, axis=-1)
    neg = m == -jnp.inf
    p = jnp.where(neg[..., None], 0.0, jnp.exp(s - m[..., None]))
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bkgqs,bskd->bqkgd", p, v.astype(jnp.float32))
    o = o / jnp.moveaxis(jnp.maximum(l, 1e-30), 3, 1)[..., None]
    lse = jnp.where(neg, -jnp.inf, m + jnp.log(jnp.maximum(l, 1e-30)))
    return (o.reshape(b, sq, h, d),
            lse.reshape(b, hk * g, sq))


def _merge_blocks(o, lse, ob, lseb):
    """Online merge of two block-normalized attention results.
    o (b,sq,h,d) f32, lse (b,h,sq)."""
    lse_new = jnp.logaddexp(lse, lseb)
    dead = jnp.isneginf(lse_new)
    wa = jnp.where(dead, 0.0, jnp.exp(lse - lse_new))
    wb = jnp.where(dead, 0.0, jnp.exp(lseb - lse_new))
    # weights are (b, h, sq) -> broadcast over (b, sq, h, d)
    wa4 = jnp.moveaxis(wa, 1, 2)[..., None]
    wb4 = jnp.moveaxis(wb, 1, 2)[..., None]
    return o * wa4 + ob.astype(o.dtype) * wb4, lse_new


def ring_attention_spmd(q, k, v, *, mesh: Mesh, axis: str = "sep",
                        causal: bool = True, scale: Optional[float] = None):
    """Ring attention over the seq-sharded ``axis``.

    q/k/v: (b, s, h, d) with s sharded over ``axis`` (global views).
    Each of the S steps computes one (q-shard × kv-shard) block —
    through the Pallas ``flash_block`` kernel when shapes tile (VERDICT
    r2 missing #4; O(s/S) memory inside the block, GQA without K/V
    repeat) — then merges (o, lse) pairs online and rotates K/V one hop
    via ``ppermute``. Blocks strictly above the causal diagonal skip
    compute entirely (lax.cond). Differentiable end-to-end: the block
    kernel's custom VJP takes both o- and lse-cotangents and the
    reverse ring is scan/ppermute transposition."""
    from ..ops.pallas import flash_attention as fa
    S = sep_degree(mesh, axis)
    scale_ = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if S == 1:
        return fa.sdpa(q, k, v, None, is_causal=causal, scale=scale_)
    if q.shape[2] % k.shape[2] != 0:
        k, v = _repeat_kv(q, k, v)
    use_pallas = fa._pallas_available()

    def block(qb, kb, vb, blk_causal):
        if use_pallas:
            out = fa.flash_block(qb, kb, vb, is_causal=blk_causal,
                                 scale=scale_)
            if out is not None:
                fa.LAST_DISPATCH = "ring_pallas"
                # merge runs in f32 and the masked lax.cond branch
                # returns f32 — bf16 block output must match
                return out[0].astype(jnp.float32), out[1]
        fa.LAST_DISPATCH = "ring_xla"
        return _xla_block(qb, kb, vb, blk_causal, scale_)

    def inner(ql, kl, vl):
        b, sl, h, d = ql.shape
        idx = jax.lax.axis_index(axis)
        o0 = jnp.zeros((b, sl, h, d), jnp.float32)
        lse0 = jnp.full((b, h, sl), -jnp.inf, jnp.float32)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def step_fn(carry, step):
            o, lse, kc, vc = carry
            # after `step` rotations this device holds shard (idx - step)
            j = (idx - step) % S

            def diag(_):
                return block(ql, kc, vc, True)

            def offdiag(_):
                def full(_):
                    return block(ql, kc, vc, False)

                def masked(_):
                    # kv shard strictly in the future: contributes
                    # nothing — skip the matmuls entirely
                    return (jnp.zeros((b, sl, h, d), jnp.float32),
                            jnp.full((b, h, sl), -jnp.inf, jnp.float32))
                return jax.lax.cond(j < idx, full, masked, None)

            if causal:
                ob, lseb = jax.lax.cond(j == idx, diag, offdiag, None)
            else:
                ob, lseb = block(ql, kc, vc, False)
            o, lse = _merge_blocks(o, lse, ob, lseb)
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            return (o, lse, kc, vc), None

        (o, lse, _, _), _ = jax.lax.scan(
            step_fn, (o0, lse0, kl, vl), jnp.arange(S))
        return o.astype(ql.dtype)

    spec = P(None, axis, None, None)
    # check_vma=False: pallas_call out_shapes carry no varying-axis
    # metadata, so the vma checker can't see through flash_block
    return jax.shard_map(inner, mesh=mesh, axis_names={axis},
                         in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)


def ulysses_attention_spmd(q, k, v, *, mesh: Mesh, axis: str = "sep",
                           causal: bool = True,
                           scale: Optional[float] = None):
    """DeepSpeed-Ulysses SEP: all_to_all swaps seq-sharding for
    head-sharding, full-sequence flash attention runs locally on h/S
    heads, and a second all_to_all swaps back.  Cheaper than the ring when
    h >= S and the full sequence fits (comm volume 2·bshd/S vs the ring's
    (S-1)·2·bshd/S)."""
    from ..ops.pallas import flash_attention as fa
    S = sep_degree(mesh, axis)
    scale_ = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if S == 1:
        return fa.sdpa(q, k, v, None, is_causal=causal, scale=scale_)
    if q.shape[2] % S != 0:
        raise ValueError(f"num_heads={q.shape[2]} not divisible by "
                         f"sep degree {S} (required for Ulysses)")
    if k.shape[2] % S != 0:
        # kv heads don't split over the axis — materialize the repeat
        # (comm then carries repeated KV); divisible GQA stays grouped
        # and sdpa's kernels handle it without repeat
        k, v = _repeat_kv(q, k, v)

    def inner(ql, kl, vl):
        def fwd(x):   # (b, s/S, h, d) -> (b, s, h/S, d)
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)
        from ..ops.pallas.flash_attention import sdpa
        out = sdpa(fwd(ql), fwd(kl), fwd(vl), None, is_causal=causal,
                   scale=scale_)
        # (b, s, h/S, d) -> (b, s/S, h, d)
        return jax.lax.all_to_all(out, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    spec = P(None, axis, None, None)
    return jax.shard_map(inner, mesh=mesh, axis_names={axis},
                         in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)


class RingAttention:
    """Layer-ish façade (PaddleNLP RingFlashAttention parity): callable on
    Tensor q/k/v; picks the active mesh's sep axis."""

    def __init__(self, axis: str = "sep", mode: str = "ring"):
        self.axis = axis
        self.mode = mode

    def __call__(self, q, k, v, causal=True):
        from ..tensor import Tensor, apply_op
        from .mesh import get_current_mesh
        mesh = get_current_mesh()
        fn = ring_attention_spmd if self.mode == "ring" \
            else ulysses_attention_spmd
        if mesh is None or self.axis not in mesh.axis_names:
            from ..ops.pallas.flash_attention import _xla_sdpa

            def f(qv, kv, vv):
                return _xla_sdpa(qv, kv, vv, None, causal, 0.0, None)
            return apply_op(f, q, k, v)

        def f(qv, kv, vv):
            return fn(qv, kv, vv, mesh=mesh, axis=self.axis, causal=causal)
        return apply_op(f, q, k, v)
