"""Context / sequence parallelism for long sequences.

Reference parity: (1) the SEP/Ulysses axis of HybridCommunicateGroup
(python/paddle/distributed/fleet/base/topology.py — verify):
DeepSpeed-Ulysses-style all-to-all swapping seq-sharding for head-sharding
around attention; (2) ring flash attention (ecosystem
PaddleNLP ring_flash_attention.py, enabled by the core flash-attn kernel's
softmax_lse output — SURVEY §2.3 CP row).

TPU-native design (SURVEY §5): the sequence axis is a first-class mesh
dim.  Ring attention = shard_map over the axis with KV blocks rotating via
``ppermute`` over ICI and an online-softmax merge (the softmax_lse the
reference threads between kernel calls is just the (m, l) accumulator pair
here).  Ulysses = two ``all_to_all``s around a plain flash attention.
Both are differentiable (ppermute/all_to_all have transpose rules), so
the backward pass is the reverse ring — no hand-written grad kernels.

Layout convention is paddle's bshd: (batch, seq, num_heads, head_dim).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["ring_attention_spmd", "ulysses_attention_spmd",
           "RingAttention", "sep_degree"]


def sep_degree(mesh: Optional[Mesh], axis: str = "sep") -> int:
    if mesh is None or axis not in mesh.axis_names:
        return 1
    return int(mesh.shape[axis])


def _repeat_kv(q, k, v):
    if k.shape[2] != q.shape[2]:  # GQA: repeat kv heads to match q
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


def ring_attention_spmd(q, k, v, *, mesh: Mesh, axis: str = "sep",
                        causal: bool = True, scale: Optional[float] = None):
    """Ring attention over the seq-sharded ``axis``.

    q/k/v: (b, s, h, d) with s sharded over ``axis`` (global views).
    Each of the S steps computes one (q-shard × kv-shard) block with the
    flash online-softmax update, then rotates K/V one hop around the ring.
    Peak memory per device: O(s/S × s/S) scores + two KV shards.
    """
    S = sep_degree(mesh, axis)
    scale_ = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    k, v = _repeat_kv(q, k, v)
    if S == 1:
        from ..ops.pallas.flash_attention import _xla_sdpa
        return _xla_sdpa(q, k, v, None, causal, 0.0, scale_)

    def inner(ql, kl, vl):
        b, sl, h, d = ql.shape
        idx = jax.lax.axis_index(axis)
        qpos = idx * sl + jnp.arange(sl)
        qf = ql.astype(jnp.float32)

        def vary(x):
            return jax.lax.pcast(x, (axis,), to="varying")
        m0 = vary(jnp.full((b, h, sl), -jnp.inf, jnp.float32))
        l0 = vary(jnp.zeros((b, h, sl), jnp.float32))
        o0 = vary(jnp.zeros((b, h, sl, d), jnp.float32))
        perm = [(i, (i + 1) % S) for i in range(S)]

        def step_fn(carry, step):
            m, l, o, kc, vc = carry
            # after `step` rotations this device holds shard (idx - step)
            j = (idx - step) % S
            kpos = j * sl + jnp.arange(sl)
            s = jnp.einsum("bqhd,bkhd->bhqk", qf,
                           kc.astype(jnp.float32),
                           preferred_element_type=jnp.float32) * scale_
            if causal:
                mask = qpos[:, None] >= kpos[None, :]
                s = jnp.where(mask[None, None], s, -jnp.inf)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            neg = m_new == -jnp.inf  # row fully masked so far
            p = jnp.where(neg[..., None], 0.0,
                          jnp.exp(s - m_new[..., None]))
            alpha = jnp.where(neg, 1.0, jnp.exp(m - m_new))
            l = l * alpha + jnp.sum(p, axis=-1)
            o = o * alpha[..., None] + jnp.einsum(
                "bhqk,bkhd->bhqd", p, vc.astype(jnp.float32))
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            return (m_new, l, o, kc, vc), None

        (m, l, o, _, _), _ = jax.lax.scan(
            step_fn, (m0, l0, o0, kl, vl), jnp.arange(S))
        out = o / jnp.maximum(l, 1e-30)[..., None]
        return jnp.einsum("bhqd->bqhd", out).astype(ql.dtype)

    spec = P(None, axis, None, None)
    return jax.shard_map(inner, mesh=mesh, axis_names={axis},
                         in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)


def ulysses_attention_spmd(q, k, v, *, mesh: Mesh, axis: str = "sep",
                           causal: bool = True,
                           scale: Optional[float] = None):
    """DeepSpeed-Ulysses SEP: all_to_all swaps seq-sharding for
    head-sharding, full-sequence flash attention runs locally on h/S
    heads, and a second all_to_all swaps back.  Cheaper than the ring when
    h >= S and the full sequence fits (comm volume 2·bshd/S vs the ring's
    (S-1)·2·bshd/S)."""
    S = sep_degree(mesh, axis)
    scale_ = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    k, v = _repeat_kv(q, k, v)
    if S == 1:
        from ..ops.pallas.flash_attention import _xla_sdpa
        return _xla_sdpa(q, k, v, None, causal, 0.0, scale_)
    if q.shape[2] % S != 0:
        raise ValueError(f"num_heads={q.shape[2]} not divisible by "
                         f"sep degree {S} (required for Ulysses)")

    def inner(ql, kl, vl):
        def fwd(x):   # (b, s/S, h, d) -> (b, s, h/S, d)
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)
        from ..ops.pallas.flash_attention import sdpa
        out = sdpa(fwd(ql), fwd(kl), fwd(vl), None, is_causal=causal,
                   scale=scale_)
        # (b, s, h/S, d) -> (b, s/S, h, d)
        return jax.lax.all_to_all(out, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    spec = P(None, axis, None, None)
    return jax.shard_map(inner, mesh=mesh, axis_names={axis},
                         in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)


class RingAttention:
    """Layer-ish façade (PaddleNLP RingFlashAttention parity): callable on
    Tensor q/k/v; picks the active mesh's sep axis."""

    def __init__(self, axis: str = "sep", mode: str = "ring"):
        self.axis = axis
        self.mode = mode

    def __call__(self, q, k, v, causal=True):
        from ..tensor import Tensor, apply_op
        from .mesh import get_current_mesh
        mesh = get_current_mesh()
        fn = ring_attention_spmd if self.mode == "ring" \
            else ulysses_attention_spmd
        if mesh is None or self.axis not in mesh.axis_names:
            from ..ops.pallas.flash_attention import _xla_sdpa

            def f(qv, kv, vv):
                return _xla_sdpa(qv, kv, vv, None, causal, 0.0, None)
            return apply_op(f, q, k, v)

        def f(qv, kv, vv):
            return fn(qv, kv, vv, mesh=mesh, axis=self.axis, causal=causal)
        return apply_op(f, q, k, v)
