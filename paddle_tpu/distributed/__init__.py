"""paddle_tpu.distributed — the distributed stack.

Reference parity: python/paddle/distributed/ (fleet, collective
communication, auto_parallel, launch — verify). TPU-native design
(SURVEY §2.4/§7): "process group" ≡ (Mesh, axis subset); collectives ≡ XLA
collectives emitted by GSPMD or explicit shard_map; rendezvous ≡
jax.distributed.initialize.
"""
from .parallel import (init_parallel_env, get_rank, get_world_size,
                       ParallelEnv, DataParallel)                 # noqa
from .communication import (all_reduce, all_gather, all_gather_object,
                            reduce_scatter, broadcast, scatter, gather,
                            reduce, alltoall, alltoall_single, send, recv,
                            global_scatter, global_gather,
                            barrier, new_group, get_group, wait, stream,
                            ReduceOp, P2POp, batch_isend_irecv, irecv, isend)  # noqa
from .mesh import (HybridCommunicateGroup, get_hybrid_communicate_group,
                   build_device_mesh)                             # noqa
from .auto_parallel_api import (ProcessMesh, shard_tensor, dtensor_from_fn,
                                reshard, Shard, Replicate, Partial,
                                Placement, shard_layer, shard_optimizer,
                                to_static, DistAttr, Engine, DistModel)  # noqa
dist_to_static = to_static  # back-compat alias
from . import fleet                                               # noqa
from . import checkpoint                                          # noqa
from . import sharding                                            # noqa
# hierarchical/quantized collectives + gradient bucketing (in-graph
# data plane; the eager control plane above is .communication)
from . import collectives                                         # noqa
from .sharding import group_sharded_parallel, save_group_sharded_model  # noqa
from .launch_utils import spawn                                   # noqa
# rendezvous KV store (C++ libptcore server/client; reference:
# paddle/phi/core/distributed/store/tcp_store — verify)
from ..core.native_api import TCPStore, MasterDaemon              # noqa
from . import launch                                              # noqa
from . import elastic                                             # noqa
from . import consistency                                         # noqa
from .consistency import (program_fingerprint,                    # noqa
                          check_program_consistency)

from . import rpc                                                 # noqa
from . import utils                                               # noqa
from . import ps                                                  # noqa
from .checkpoint import save_state_dict, load_state_dict          # noqa
from .fleet import DistributedStrategy as Strategy                # noqa
from .parallel_layers import split, unshard_dtensor, shard_dataloader  # noqa

# short aliases matching paddle.distributed.*
is_initialized = parallel_initialized = \
    lambda: ParallelEnv().world_size >= 1


def destroy_process_group(group=None):
    """Release a comm group (reference: dist.destroy_process_group).
    Mesh-axis groups own no persistent native resources here — XLA
    collectives are per-program — so this only drops the registry
    entry (all groups when ``group`` is None)."""
    from . import communication as _c
    if group is None:
        _c._GROUPS.clear()
        return
    for k, v in list(_c._GROUPS.items()):
        if v is group:
            del _c._GROUPS[k]


def get_backend(group=None):
    """Reference parity (paddle.distributed.get_backend — verify): the
    collective backend name. Data-plane collectives are XLA-compiled
    (GSPMD over ICI/DCN); the eager control plane rides the TCPStore.
    """
    return "XLA"
