"""Distributed checkpoint with reshard-on-load, async save, and
shard-wise (bounded-memory) load.

Reference parity: python/paddle/distributed/checkpoint/
(save_state_dict/load_state_dict: per-rank shard files + global metadata,
reshard-on-load — verify; SURVEY §5 checkpoint row: "tensorstore-backed
async sharded checkpoint keyed by (global shape, sharding)").

TPU-native design: each process writes ONLY its addressable shards
(replica 0 of each index region) plus a metadata json keyed by
(global shape, per-shard index ranges). On load, each device's target
shard is assembled from just the saved pieces overlapping its region and
placed with make_array_from_single_device_arrays — the full tensor is
NEVER materialized on any host, so loading a 13B state dict needs
max(saved shard, target shard) working memory, not the global size.
bfloat16 is preserved bit-exactly (npz stores the raw 2-byte payload; the
dtype is recovered from metadata). ``async_save=True`` snapshots device
shards to host, then writes files on a background thread —
``wait_async_save()`` joins outstanding writes (call before relaunch)."""
from __future__ import annotations

import json
import os
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict", "wait_async_save",
           "AsyncSaveHandle", "atomic_write", "atomic_savez",
           "atomic_json_dump"]


def _fsync_dir(dirname: str):
    """fsync a DIRECTORY so a just-renamed entry is durable — without
    it, the rename itself can vanish on power loss even though the
    file contents were fsynced. Filesystems that refuse directory
    fds (some network/overlay mounts) degrade to content-only
    durability, same as before."""
    try:
        fd = os.open(dirname or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path: str, write_fn, mode: str = "wb"):
    """Crash-safe file write: ``write_fn(f)`` goes to a same-directory
    temp file which is fsynced and ``os.replace``d over ``path``, and
    the PARENT DIRECTORY is fsynced after the rename — a reader (or a
    restart, or a power loss) sees either the old complete file or the
    new complete file, never a torn write and never a vanished rename.
    Shared by checkpoint shards, metadata, the serving engine's
    snapshot files and the fleet's checkpoint manifests."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, mode) as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(os.path.dirname(path))
    except BaseException:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise


def atomic_savez(path: str, arrays: dict):
    """``np.savez`` through :func:`atomic_write` (npz is self-contained,
    so tmp+rename makes the whole checkpoint piece atomic)."""
    atomic_write(path, lambda f: np.savez(f, **arrays))


def atomic_json_dump(path: str, obj):
    atomic_write(path, lambda f: json.dump(obj, f), mode="w")


def _leaf_items(state_dict, prefix=""):
    for k, v in state_dict.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            yield from _leaf_items(v, key)
        else:
            yield key, v


_ASYNC: list["AsyncSaveHandle"] = []
# test/diagnostic introspection: stats of the most recent load
_last_load_stats = {"max_buffer_bytes": 0}


class AsyncSaveHandle:
    def __init__(self, thread: threading.Thread):
        self._thread = thread
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return not self._thread.is_alive()

    def result(self, timeout: Optional[float] = None):
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("checkpoint write still in progress")
        if self._error is not None:
            raise self._error


def wait_async_save():
    """Join all outstanding async checkpoint writes (reference: the
    sharded-save sync barrier before elastic relaunch)."""
    while _ASYNC:
        _ASYNC.pop().result()


def _ts_spec(path, key):
    return {"driver": "zarr",
            "kvstore": {"driver": "file",
                        "path": os.path.join(path, "ts", key)}}


def _tensor_chunks(info):
    """One chunk shape per tensor: the max shard extent per dim. GSPMD
    tiles are grid-aligned (shard i spans [i*tile, min((i+1)*tile, dim))),
    so every shard covers exactly one chunk — or a prefix of the final
    chunk that only that one writer touches. Ragged edge shards therefore
    never share a chunk with another writer, and creation and open use
    the SAME layout."""
    chunks = [1] * len(info["shape"])
    for sh in info["shards"]:
        for d, (a, b) in enumerate(sh["index"]):
            chunks[d] = max(chunks[d], b - a)
    return chunks


def _ts_open(path, key, dtype=None, shape=None, chunks=None, create=False,
             delete_existing=False):
    import tensorstore as ts
    kw = {"open": not delete_existing}
    if create:
        kw.update(create=True, dtype=ts.dtype(_np_dtype(str(dtype))),
                  shape=list(shape), delete_existing=delete_existing)
        if chunks is not None:
            kw["chunk_layout"] = ts.ChunkLayout(chunk_shape=list(chunks))
    return ts.open(_ts_spec(path, key), **kw).result()


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, async_save=False,
                    backend="npz"):
    """Write each tensor's addressable shards + global metadata.

    async_save=True: device→host transfer happens now (a consistent
    snapshot), file IO on a background thread; returns AsyncSaveHandle.

    backend="tensorstore": shards go into one chunked zarr array per
    tensor (chunk grid = the GSPMD shard grid, so concurrent multi-host
    region writes never read-modify-write the same chunk); load reads
    exactly the target region. backend="npz" keeps the self-contained
    per-process file layout."""
    os.makedirs(path, exist_ok=True)
    pidx = jax.process_index()
    meta = {}
    shard_file = os.path.join(path, f"shard_{pidx}.npz")
    arrays = {}
    ts_writes = []                       # (key, index ranges, host array)
    for key, v in _leaf_items(state_dict):
        # Partial tensors persist their DENSE (summed) value
        val = v._dense_value() if isinstance(v, Tensor) else v
        if not hasattr(val, "shape"):
            meta[key] = {"kind": "scalar", "value": val}
            continue
        val = jnp.asarray(val)
        gshape = list(val.shape)
        shards = []
        if hasattr(val, "addressable_shards"):
            for s in val.addressable_shards:
                if s.replica_id != 0:
                    continue
                idx_desc = []
                for sl, dim in zip(s.index, gshape):
                    start = sl.start or 0
                    stop = sl.stop if sl.stop is not None else dim
                    idx_desc.append([int(start), int(stop)])
                aid = f"{key}__{s.device.id}"
                arrays[aid] = np.asarray(s.data)   # snapshot to host
                shards.append({"array": aid, "index": idx_desc,
                               "file": f"shard_{pidx}.npz"})
        else:
            aid = f"{key}__0"
            arrays[aid] = np.asarray(val)
            shards.append({"array": aid,
                           "index": [[0, d] for d in gshape],
                           "file": f"shard_{pidx}.npz"})
        meta[key] = {"kind": "tensor", "shape": gshape,
                     "dtype": str(val.dtype), "shards": shards}
        if backend == "tensorstore":
            meta[key]["storage"] = "tensorstore"
            for sh in shards:
                ts_writes.append((key, sh["index"], arrays[sh["array"]]))

    # the metadata all_gather is a COLLECTIVE — it must run on the main
    # thread in deterministic order with the training step's collectives
    # (a background-thread gather would race them and hang multi-host
    # jobs); only the file IO goes to the writer thread
    metas = [meta]
    if jax.process_count() > 1:
        from .communication import all_gather_object
        gathered = []
        all_gather_object(gathered, meta)
        metas = gathered
    merged: dict = {}
    for m in metas:
        for k, info in m.items():
            if k not in merged:
                merged[k] = info
            elif info["kind"] == "tensor":
                merged[k]["shards"].extend(info["shards"])

    if backend == "tensorstore":
        # (re)create the arrays on the MAIN thread with a collective
        # barrier: the coordinator wipes any prior checkpoint whose
        # shape/chunk grid changed (overwriting with merged constraints
        # would raise), then every process opens the fresh arrays. The
        # wipe walks MERGED metadata (all tensors, once each) — not this
        # process's shards — so tensors addressable only on other hosts
        # are recreated too.
        if pidx == coordinator_rank:
            for key, info in merged.items():
                if info["kind"] != "tensor" or                         info.get("storage") != "tensorstore":
                    continue
                _ts_open(path, key, dtype=info["dtype"],
                         shape=info["shape"], chunks=_tensor_chunks(info),
                         create=True, delete_existing=True)
        if jax.process_count() > 1:
            from .communication import all_gather_object
            token = []
            all_gather_object(token, pidx)   # barrier: creation done

    def _write(handle=None):
        try:
            if backend == "tensorstore":
                futures = []
                opened = {}
                for key, idx, host in ts_writes:
                    info = merged[key]
                    if key not in opened:
                        opened[key] = _ts_open(
                            path, key, dtype=info["dtype"],
                            shape=info["shape"],
                            chunks=_tensor_chunks(info), create=True)
                    sl = tuple(slice(a, b) for a, b in idx)
                    futures.append(opened[key][sl].write(host))
                for f in futures:
                    f.result()
            else:
                atomic_savez(shard_file, arrays)
            if pidx == coordinator_rank:
                # metadata lands last and atomically: WITHIN THIS
                # PROCESS its presence is the commit point — a crash
                # mid-save leaves the previous complete checkpoint or
                # no new metadata, never a torn file. Multi-host npz
                # saves keep the pre-existing contract (ranks write
                # shards independently, no cross-host barrier before
                # this write); the tensorstore backend's creation
                # barrier, or a launcher-level barrier, orders hosts
                atomic_json_dump(os.path.join(path, "metadata.json"),
                                 merged)
        except BaseException as e:     # surfaced via handle.result()
            if handle is not None:
                handle._error = e
                return
            raise

    if not async_save:
        _write()
        return None
    thread = threading.Thread(target=lambda: _write(handle), daemon=True)
    handle = AsyncSaveHandle(thread)
    thread.start()
    _ASYNC.append(handle)
    return handle


def _np_dtype(name):
    """numpy dtype for a saved dtype string, via ml_dtypes for bf16/fp8."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _read_piece(npz, s, want_dtype):
    """One saved piece as a correctly-typed numpy array (npz stores bf16
    as raw void bytes; the metadata dtype restores the view)."""
    data = np.asarray(npz[s["array"]])
    if data.dtype != want_dtype and data.dtype.itemsize == \
            want_dtype.itemsize and data.dtype.kind == "V":
        data = data.view(want_dtype)
    return data


def _assemble_region(region, shards, shard_data, saved_dtype):
    """One target region as a host buffer, filled from every saved piece
    that overlaps it (the single place all index arithmetic lives)."""
    buf = np.zeros([b - a for a, b in region], dtype=saved_dtype)
    _last_load_stats["max_buffer_bytes"] = max(
        _last_load_stats["max_buffer_bytes"], buf.nbytes)
    for s in shards:
        inter = [(max(a, sa), min(b, sb))
                 for (a, b), (sa, sb) in zip(region, s["index"])]
        if any(a >= b for a, b in inter):
            continue
        data = _read_piece(shard_data(s["file"]), s, saved_dtype)
        src_idx = tuple(slice(a - sa, b - sa)
                        for (a, b), (sa, sb) in zip(inter, s["index"]))
        dst_idx = tuple(slice(a - ra, b - ra)
                        for (a, b), (ra, rb) in zip(inter, region))
        buf[dst_idx] = data[src_idx]
    return buf


def _shard_region(tshard, gshape):
    return tuple((int(sl.start or 0),
                  int(sl.stop) if sl.stop is not None else dim)
                 for sl, dim in zip(tshard.index, gshape))


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None,
                    offload=False):
    """Fill `state_dict`'s tensors in place from `path`, resharding to
    each tensor's CURRENT sharding — shard-wise: only the saved pieces
    overlapping each target shard's region are read, each distinct
    region is assembled ONCE (replicas share the buffer), and the
    largest host buffer is one target shard, never the global tensor."""
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    cache: dict = {}
    _last_load_stats["max_buffer_bytes"] = 0

    def shard_data(fname):
        if fname not in cache:
            cache[fname] = np.load(os.path.join(path, fname))
        return cache[fname]

    ts_cache: dict = {}

    def read_region(info, key, region, saved_dtype):
        """One target region, from zarr (exact-region read) or npz
        (piece assembly)."""
        if info.get("storage") == "tensorstore":
            if key not in ts_cache:
                ts_cache[key] = _ts_open(path, key)
            arr = ts_cache[key]
            sl = tuple(slice(a, b) for a, b in region)
            buf = np.asarray(arr[sl].read().result())
            _last_load_stats["max_buffer_bytes"] = max(
                _last_load_stats["max_buffer_bytes"], buf.nbytes)
            return buf
        return _assemble_region(region, info["shards"], shard_data,
                                saved_dtype)

    for key, v in _leaf_items(state_dict):
        info = meta.get(key)
        if info is None or info["kind"] != "tensor":
            continue
        gshape = tuple(info["shape"])
        saved_dtype = _np_dtype(info["dtype"])
        tgt = v._value if isinstance(v, Tensor) else None
        if tgt is None:
            continue
        sharding = getattr(tgt, "sharding", None)
        tgt_np_dtype = _np_dtype(str(tgt.dtype))
        if sharding is not None and hasattr(tgt, "addressable_shards") \
                and len(tgt.addressable_shards) >= 1:
            # group replica devices by region: assemble each region once
            by_region: dict = {}
            for tshard in tgt.addressable_shards:
                by_region.setdefault(_shard_region(tshard, gshape),
                                     []).append(tshard.device)
            full_region = tuple((0, d) for d in gshape)
            if list(by_region) == [full_region]:
                # fully replicated: one buffer, device_put broadcasts
                buf = read_region(info, key, full_region, saved_dtype)
                v._update_value(jax.device_put(
                    buf.astype(tgt_np_dtype, copy=False), sharding))
                continue
            pieces = []
            for region, devices in by_region.items():
                buf = read_region(info, key, region, saved_dtype)
                buf = buf.astype(tgt_np_dtype, copy=False)
                pieces.extend(jax.device_put(buf, d) for d in devices)
            arr = jax.make_array_from_single_device_arrays(
                gshape, sharding, pieces)
            v._update_value(arr)
            continue
        # unsharded target: assemble the (single-device) full value
        full = read_region(info, key, tuple((0, d) for d in gshape),
                           saved_dtype)
        v._update_value(jnp.asarray(full).astype(tgt.dtype))
