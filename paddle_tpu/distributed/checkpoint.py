"""Distributed checkpoint with reshard-on-load.

Reference parity: python/paddle/distributed/checkpoint/
(save_state_dict/load_state_dict: per-rank shard files + metadata,
reshard-on-load — verify).

TPU-native design: each process writes ONLY its addressable shards plus a
metadata json keyed by (global shape, index-map). On load, any process
reads the pieces covering its target sharding — so loading onto a different
mesh/degree works by construction. Orbax/tensorstore async is the round-2
fast path; this implementation is plain npz but layout-compatible."""
from __future__ import annotations

import json
import os
import pickle
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict"]


def _leaf_items(state_dict, prefix=""):
    for k, v in state_dict.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            yield from _leaf_items(v, key)
        else:
            yield key, v


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, async_save=False):
    os.makedirs(path, exist_ok=True)
    pidx = jax.process_index()
    meta = {}
    shard_file = os.path.join(path, f"shard_{pidx}.npz")
    arrays = {}
    for key, v in _leaf_items(state_dict):
        val = v._value if isinstance(v, Tensor) else v
        if not hasattr(val, "shape"):
            meta[key] = {"kind": "scalar", "value": val}
            continue
        val = jnp.asarray(val)
        gshape = list(val.shape)
        shards = []
        if hasattr(val, "addressable_shards"):
            for s in val.addressable_shards:
                if s.replica_id != 0:
                    continue
                idx_desc = []
                for sl, dim in zip(s.index, gshape):
                    start = sl.start or 0
                    stop = sl.stop if sl.stop is not None else dim
                    idx_desc.append([int(start), int(stop)])
                aid = f"{key}__{s.device.id}"
                arrays[aid] = np.asarray(s.data)
                shards.append({"array": aid, "index": idx_desc,
                               "file": f"shard_{pidx}.npz"})
        else:
            aid = f"{key}__0"
            arrays[aid] = np.asarray(val)
            shards.append({"array": aid,
                           "index": [[0, d] for d in gshape],
                           "file": f"shard_{pidx}.npz"})
        meta[key] = {"kind": "tensor", "shape": gshape,
                     "dtype": str(val.dtype), "shards": shards}
    np.savez(shard_file, **arrays)
    metas = [meta]
    if jax.process_count() > 1:
        from .communication import all_gather_object
        gathered = []
        all_gather_object(gathered, meta)
        metas = gathered
    if pidx == coordinator_rank:
        merged: dict = {}
        for m in metas:
            for k, info in m.items():
                if k not in merged:
                    merged[k] = info
                elif info["kind"] == "tensor":
                    merged[k]["shards"].extend(info["shards"])
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(merged, f)


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None,
                    offload=False):
    """Fill `state_dict`'s tensors in place from `path`, resharding to each
    tensor's CURRENT sharding."""
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    cache: dict = {}

    def shard_data(fname):
        if fname not in cache:
            cache[fname] = np.load(os.path.join(path, fname))
        return cache[fname]

    for key, v in _leaf_items(state_dict):
        info = meta.get(key)
        if info is None or info["kind"] != "tensor":
            continue
        full = np.zeros(info["shape"], dtype=np.dtype(
            info["dtype"] if info["dtype"] != "bfloat16" else "float32"))
        for s in info["shards"]:
            data = np.asarray(shard_data(s["file"])[s["array"]])
            idx = tuple(slice(a, b) for a, b in s["index"])
            full[idx] = data.astype(full.dtype)
        if isinstance(v, Tensor):
            tgt = v._value
            arr = jnp.asarray(full, dtype=tgt.dtype)
            if hasattr(tgt, "sharding"):
                arr = jax.device_put(arr, tgt.sharding)  # reshard-on-load
            v._update_value(arr)
