"""User-level RPC (reference: python/paddle/distributed/rpc/ +
paddle/fluid/distributed/rpc/ RpcAgent over brpc — verify).

TPU-native design: the reference ships a brpc C++ agent; here the agent is
a length-prefixed-pickle protocol over raw TCP sockets — the same
host-side control-plane transport class as the C++ TCPStore (which this
module reuses for endpoint rendezvous). RPC is a coordination surface
(parameter-server control, custom user plumbing), never the tensor perf
path — bulk tensors move inside jitted XLA programs.

Protocol: 8-byte big-endian length + pickle of (fn, args, kwargs);
response is length + pickle of ("ok"|"err", payload). Functions must be
picklable (importable top-level callables), as in the reference.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
from dataclasses import dataclass
from typing import Any, Optional

from ..utils.flags import env_int, env_str

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos", "WorkerInfo"]


@dataclass(frozen=True)
class WorkerInfo:
    name: str
    rank: int
    ip: str
    port: int

    @property
    def endpoint(self):
        return f"{self.ip}:{self.port}"


_AGENT = None
_AGENT_LOCK = threading.Lock()


def _send_msg(sock: socket.socket, payload: bytes):
    sock.sendall(struct.pack(">Q", len(payload)) + payload)


def _recv_msg(sock: socket.socket) -> bytes:
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("rpc peer closed connection")
        hdr += chunk
    n = struct.unpack(">Q", hdr)[0]
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("rpc peer closed mid-message")
        buf.extend(chunk)
    return bytes(buf)


class _Agent:
    """Per-process RPC agent: a serving thread + a client connection pool."""

    def __init__(self, name: str, rank: int, world_size: int):
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self.workers: dict[str, WorkerInfo] = {}
        self._by_rank: dict[int, WorkerInfo] = {}
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("0.0.0.0", 0))
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        self.ip = env_str("PADDLE_LOCAL_IP", "127.0.0.1")
        self._stop = threading.Event()
        self._conns: dict[str, socket.socket] = {}
        self._locks: dict[str, threading.Lock] = {}
        self._conn_lock = threading.Lock()
        t = threading.Thread(target=self._serve_loop, daemon=True,
                             name=f"rpc-serve-{name}")
        t.start()
        self._serve_thread = t

    # -- server side --------------------------------------------------------
    def _serve_loop(self):
        try:
            self._srv.settimeout(0.2)
        except OSError:
            return
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            # daemon handler threads are fire-and-forget: retaining them
            # would leak one Thread object per client connection
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket):
        try:
            while not self._stop.is_set():
                try:
                    req = _recv_msg(conn)
                except (ConnectionError, OSError):
                    return
                try:
                    fn, args, kwargs = pickle.loads(req)
                    if fn == "__ping__":
                        out = ("ok", self.name)
                    else:
                        out = ("ok", fn(*args, **(kwargs or {})))
                except BaseException as e:  # delivered to the caller
                    out = ("err", e)
                try:
                    payload = pickle.dumps(out)
                except Exception as e:  # TypeError for locks/sockets, etc.
                    payload = pickle.dumps(
                        ("err", RuntimeError(
                            "rpc result not picklable "
                            f"({type(out[1]).__name__}): {e}")))
                _send_msg(conn, payload)
        finally:
            conn.close()

    # -- client side --------------------------------------------------------
    def _conn_to(self, info: WorkerInfo):
        with self._conn_lock:
            s = self._conns.get(info.name)
            if s is None:
                s = socket.create_connection((info.ip, info.port),
                                             timeout=60)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._conns[info.name] = s
                self._locks[info.name] = threading.Lock()
            return s, self._locks[info.name]

    def _evict(self, name):
        """Drop a connection whose request/response stream may be out of
        sync (timeout or transport error mid-call): a late reply on a
        reused socket would otherwise be read as the NEXT call's result."""
        with self._conn_lock:
            sock = self._conns.pop(name, None)
            self._locks.pop(name, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def call(self, to, fn, args, kwargs, timeout=None):
        info = self.resolve(to)
        payload = pickle.dumps((fn, tuple(args or ()), dict(kwargs or {})))
        # one in-flight request per connection: serialize on the socket
        s, lock = self._conn_to(info)
        with lock:
            try:
                if timeout is not None:
                    s.settimeout(timeout)
                _send_msg(s, payload)
                status, result = pickle.loads(_recv_msg(s))
            except (socket.timeout, ConnectionError, OSError):
                self._evict(info.name)
                raise
            finally:
                try:
                    s.settimeout(None)
                except OSError:
                    pass
        if status == "err":
            raise result
        return result

    def ping(self, info: WorkerInfo, timeout=5.0) -> bool:
        try:
            s, lock = self._conn_to(info)
            with lock:
                s.settimeout(timeout)
                _send_msg(s, pickle.dumps(("__ping__", (), {})))
                status, _ = pickle.loads(_recv_msg(s))
                s.settimeout(None)
            return status == "ok"
        except Exception:
            # the socket may hold a late ping reply; a reused connection
            # would read it as the NEXT call's result — evict at the
            # source instead of relying on callers to drop_conn
            self._evict(info.name)
            return False

    def drop_conn(self, name):
        self._evict(name)

    def resolve(self, to) -> WorkerInfo:
        if isinstance(to, WorkerInfo):
            return to
        if isinstance(to, int):
            if to not in self._by_rank:
                raise ValueError(f"unknown rpc rank {to}")
            return self._by_rank[to]
        if to not in self.workers:
            raise ValueError(
                f"unknown rpc worker {to!r}; known: {sorted(self.workers)}")
        return self.workers[to]

    def stop(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._conn_lock:
            for s in self._conns.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._conns.clear()
            self._locks.clear()


class FutureWrapper:
    """rpc_async return value (paddle .wait() parity)."""

    def __init__(self):
        self._ev = threading.Event()
        self._val: Any = None
        self._exc: Optional[BaseException] = None

    def _fulfill(self, val=None, exc=None):
        self._val, self._exc = val, exc
        self._ev.set()

    def wait(self, timeout=None):
        if not self._ev.wait(timeout):
            raise TimeoutError("rpc_async result not ready")
        if self._exc is not None:
            raise self._exc
        return self._val

    def done(self):
        return self._ev.is_set()


def _store():
    from . import communication
    return communication._get_store()


def init_rpc(name: str, rank: int = None, world_size: int = None,
             master_endpoint: str = None):
    """Start this process's RPC agent and rendezvous with peers.

    ``master_endpoint`` (host:port) defaults to the launch contract's
    PADDLE_MASTER; endpoint exchange rides the C++ TCPStore."""
    global _AGENT
    with _AGENT_LOCK:
        if _AGENT is not None:
            raise RuntimeError("init_rpc called twice (call shutdown first)")
        if master_endpoint is not None:
            # explicit argument overrides any inherited env default
            os.environ["PADDLE_MASTER"] = master_endpoint
        rank = env_int("PADDLE_TRAINER_ID", 0) if rank is None \
            else int(rank)
        world_size = env_int("PADDLE_TRAINERS_NUM", 1) \
            if world_size is None else int(world_size)
        agent = _Agent(name, rank, world_size)
        store = _store()
        # generation = joiner cohort: every round makes exactly world_size
        # init_rpc calls, so a shared monotone joiner counter assigns each
        # cohort a unique generation — endpoint keys are scoped by it, so a
        # re-init can never read a previous (dead) round's endpoints, and
        # no shutdown-counter arithmetic can race or brick rendezvous.
        joiner = store.add("rpc/joiners", 1)
        gen = (joiner - 1) // world_size
        agent.generation = gen
        info = WorkerInfo(name, rank, agent.ip, agent.port)
        store.set(f"rpc/{gen}/worker/{rank}", pickle.dumps(info))
        deadline = time.time() + 120
        try:
            for r in range(world_size):
                key = f"rpc/{gen}/worker/{r}"
                while True:
                    data = None
                    try:
                        data = store.get(key)
                    except Exception:
                        pass
                    if data:
                        winfo = pickle.loads(data)
                        # liveness-validate: a partially-failed earlier
                        # round can leave stale endpoints under this
                        # generation; never rendezvous with a dead peer —
                        # on ping failure re-read the key (a live cohort
                        # member overwrites its slot) until the deadline
                        if winfo.rank == rank or agent.ping(winfo):
                            break
                        agent.drop_conn(winfo.name)
                    if time.time() > deadline:
                        raise TimeoutError(
                            f"rpc rendezvous timed out on {key} — if a "
                            "previous init round failed part-way, restart "
                            "the rendezvous master (stale store state "
                            "fails loudly rather than joining dead peers)")
                    time.sleep(0.05)
                agent.workers[winfo.name] = winfo
                agent._by_rank[winfo.rank] = winfo
        except BaseException:
            agent.stop()        # never leak a serving agent on failure
            raise
        _AGENT = agent
        return agent


def _agent() -> _Agent:
    if _AGENT is None:
        raise RuntimeError("rpc not initialized — call init_rpc first")
    return _AGENT


def rpc_sync(to, fn, args=None, kwargs=None, timeout=None):
    """Call ``fn(*args, **kwargs)`` on worker ``to`` (name or rank) and
    block for the result."""
    return _agent().call(to, fn, args, kwargs, timeout)


def rpc_async(to, fn, args=None, kwargs=None, timeout=None):
    """Like rpc_sync but returns a future with .wait()."""
    fut = FutureWrapper()

    def run():
        try:
            fut._fulfill(val=_agent().call(to, fn, args, kwargs, timeout))
        except BaseException as e:
            fut._fulfill(exc=e)
    threading.Thread(target=run, daemon=True).start()
    return fut


def get_worker_info(name: str = None) -> WorkerInfo:
    a = _agent()
    if name is None:
        return a.workers[a.name]
    return a.resolve(name)


def get_all_worker_infos():
    return sorted(_agent().workers.values(), key=lambda w: w.rank)


def shutdown():
    """Graceful stop: barrier over the store so no peer is mid-call, then
    close the agent."""
    global _AGENT
    with _AGENT_LOCK:
        if _AGENT is None:
            return
        store = _store()
        # per-generation barrier: isolated key, so a dead peer only means
        # this round's barrier times out — future rounds are unaffected
        key = f"rpc/{_AGENT.generation}/shutdown"
        n = store.add(key, 1)
        deadline = time.time() + 60
        while n < _AGENT.world_size:
            if time.time() > deadline:
                break  # peer died mid-round; nothing to repair
            time.sleep(0.05)
            n = store.add(key, 0)
        _AGENT.stop()
        _AGENT = None
