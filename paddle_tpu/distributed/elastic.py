"""Elastic training manager.

Reference parity: python/paddle/distributed/fleet/elastic/manager.py —
verify (etcd-backed node registry, watch for join/leave within
[min_np, max_np], kill-and-relaunch with new ranks; recovery is
checkpoint-resume, not in-flight).

TPU-native design: the registry is the C++ TCPStore instead of etcd
(one fewer external service); membership is heartbeat keys with
host-side expiry. A scale event (node count change within bounds)
bumps a generation counter — workers watching the generation exit
cleanly and the launcher relaunches them with the new world size,
resuming from the latest async checkpoint (SURVEY §5: slice failure →
relaunch + fast-resume)."""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from ..core.native_api import TCPStore

__all__ = ["ElasticManager", "ElasticStatus"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """Node-membership tracker over a TCPStore.

    Each node heartbeats ``elastic/node/{id}`` with a timestamp; the
    manager counts nodes with fresh heartbeats. When the count changes
    while min_np <= count <= max_np, the generation key is bumped: all
    nodes observe it and return RESTART from watch().
    """

    def __init__(self, host: str, port: int, node_id: Optional[str] = None,
                 min_np: int = 1, max_np: int = 0,
                 heartbeat_interval: float = 1.0,
                 heartbeat_timeout: float = 5.0):
        self.node_id = node_id or f"{os.uname().nodename}-{os.getpid()}"
        self.min_np = min_np
        self.max_np = max_np or (1 << 30)
        self.heartbeat_interval = heartbeat_interval
        self.heartbeat_timeout = heartbeat_timeout
        self._store = TCPStore(host, port)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_count = 0
        # node -> (last seen heartbeat counter, local monotonic time it
        # changed). Liveness = counter advanced recently BY OUR CLOCK, so
        # cross-host wall-clock skew cannot fake a death.
        self._hb_seen: dict = {}

    # -- membership ---------------------------------------------------------

    def register(self):
        """Join the cluster and start heartbeating. Membership updates go
        through the store's atomic add (slot counter + per-slot key), so
        concurrent joins cannot lose each other."""
        slot = self._store.add("elastic/nslots", 1)
        self._store.set(f"elastic/member/{slot}", self.node_id)
        # a relaunched node reuses its node_id: clear any tombstone from
        # the previous generation or it stays excluded forever
        self._store.delete_key(f"elastic/left/{self.node_id}")
        self._beat()
        self._thread = threading.Thread(target=self._heartbeat_loop,
                                        daemon=True)
        self._thread.start()
        self._last_count = len(self.alive_nodes())

    def deregister(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        self._store.set(f"elastic/left/{self.node_id}", "1")

    def _known_nodes(self):
        if not self._store.check("elastic/nslots"):
            return []
        n = self._store.add("elastic/nslots", 0)
        nodes = []
        for slot in range(1, n + 1):
            key = f"elastic/member/{slot}"
            if not self._store.check(key):
                continue
            node = self._store.get(key).decode()
            if node and not self._store.check(f"elastic/left/{node}") \
                    and node not in nodes:
                nodes.append(node)
        return nodes

    def _beat(self):
        self._store.add(f"elastic/hb/{self.node_id}", 1)

    def _heartbeat_loop(self):
        while not self._stop.wait(self.heartbeat_interval):
            try:
                self._beat()
            except ConnectionError:
                return

    def alive_nodes(self):
        now = time.monotonic()
        alive = []
        for n in self._known_nodes():
            key = f"elastic/hb/{n}"
            if not self._store.check(key):
                continue
            counter = self._store.add(key, 0)
            seen = self._hb_seen.get(n)
            if seen is None or counter != seen[0]:
                self._hb_seen[n] = (counter, now)
                alive.append(n)
            elif now - seen[1] <= self.heartbeat_timeout:
                alive.append(n)
        return alive

    # -- scale watch --------------------------------------------------------

    @property
    def generation(self) -> int:
        if not self._store.check("elastic/generation"):
            return 0
        return int(self._store.get("elastic/generation").decode())

    def _bump_generation(self):
        gen = self._store.add("elastic/generation_counter", 1)
        self._store.set("elastic/generation", str(gen))
        return gen

    def watch(self, poll: float = 0.5,
              should_stop: Optional[Callable[[], bool]] = None) -> str:
        """Block until a scale event / completion; returns ElasticStatus."""
        seen_gen = self.generation
        while True:
            if should_stop is not None and should_stop():
                return ElasticStatus.COMPLETED
            count = len(self.alive_nodes())
            if count != self._last_count:
                if count < self.min_np:
                    # below quorum: hold until nodes return or exceed
                    self._last_count = count
                    if count == 0:
                        return ElasticStatus.ERROR
                    # stay in HOLD by continuing the loop
                elif count <= self.max_np:
                    self._last_count = count
                    self._bump_generation()
                    return ElasticStatus.RESTART
            if self.generation != seen_gen:
                return ElasticStatus.RESTART
            time.sleep(poll)

    def close(self):
        try:
            self.deregister()
        finally:
            self._store.close()
