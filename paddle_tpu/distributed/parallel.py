"""Process-level parallel env + DataParallel façade.

Reference parity: python/paddle/distributed/parallel.py (init_parallel_env,
ParallelEnv, DataParallel w/ C++ Reducer grad bucketing — verify).

TPU-native design: rendezvous is ``jax.distributed.initialize`` (PJRT
coordination service ≡ TCPStore). DataParallel needs no Reducer: data
parallelism is SPMD — the batch is sharded over the "dp" mesh axis and XLA
emits the fused gradient all-reduce inside the jitted step (bucketing +
overlap come from XLA's latency-hiding scheduler)."""
from __future__ import annotations

import os

import jax
import numpy as np

from ..nn.layer import Layer
from ..utils.flags import env_int, env_str
from ..tensor import Tensor

__all__ = ["init_parallel_env", "get_rank", "get_world_size", "ParallelEnv",
           "DataParallel"]

_INITIALIZED = False


def init_parallel_env():
    """Multi-host init from env contract (PADDLE_TRAINER_ID /
    PADDLE_TRAINERS_NUM / PADDLE_MASTER honored for parity; JAX-native
    COORDINATOR_ADDRESS etc. also works)."""
    global _INITIALIZED
    if _INITIALIZED:
        return ParallelEnv()
    n = env_int("PADDLE_TRAINERS_NUM", env_int("JAX_NUM_PROCESSES", 1))
    # probe the coordination client WITHOUT touching the backend:
    # jax.process_count() would initialize XLA and make the subsequent
    # jax.distributed.initialize() unconditionally raise (found by the
    # process-level golden test — tests/test_process_golden.py)
    try:
        from jax._src import distributed as _jdist
        already = getattr(_jdist.global_state, "client", None) is not None
    except Exception:
        already = False   # probe unavailable: let initialize() decide
    if n > 1 and not already:
        coord = env_str("PADDLE_MASTER", "") \
            or env_str("JAX_COORDINATOR_ADDRESS", "") or None
        pid = env_int("PADDLE_TRAINER_ID", env_int("JAX_PROCESS_ID", 0))
        try:
            jax.distributed.initialize(coordinator_address=coord,
                                       num_processes=n, process_id=pid)
        except RuntimeError as e:
            # double init, or backend already up in a process that never
            # needed the coordination service — don't take down a job
            # that may still work via the store transport
            msg = str(e).lower()
            if ("already" not in msg
                    and "must be called before" not in msg):
                raise
    _INITIALIZED = True
    return ParallelEnv()


def get_rank(group=None):
    if group is not None:
        return group.get_group_rank(jax.process_index())
    return jax.process_index()


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    return jax.process_count()


class ParallelEnv:
    @property
    def rank(self):
        return jax.process_index()

    @property
    def local_rank(self):
        return jax.process_index()

    @property
    def world_size(self):
        return jax.process_count()

    @property
    def nranks(self):
        return jax.process_count()

    @property
    def device_id(self):
        return 0

    @property
    def dev_id(self):
        return 0

    @property
    def current_endpoint(self):
        return env_str("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:0")

    @property
    def trainer_endpoints(self):
        eps = env_str("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []


class DataParallel(Layer):
    """Wrapper marking a model data-parallel.

    Under SPMD there is nothing to bucket: forward with a dp-sharded batch
    under jit makes XLA insert one fused grad all-reduce (reference's
    Reducer+fused allreduce — paddle/fluid/imperative/reducer.cc — verify).
    The wrapper keeps paddle's API (`no_sync`, `scale_loss`) and annotates
    the model so TrainStep shards inputs over the "dp" axis."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_dp_inner", layers)
        self._data_parallel_mode = True
        # reference comm_buffer_size is in MB — it sizes the fusion
        # buffers of the explicit (eager / shard_map) sync path below;
        # the jitted GSPMD path ignores it
        self._comm_buffer_bytes = int(comm_buffer_size) << 20
        self._dp_group = group
        self._no_sync = False

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def sync_gradients(self, parameters=None):
        """Explicit bucketed mean-all-reduce of gradients across the
        dp group — for MANUAL eager loops ported from the reference
        (backward() ... sync_gradients() ... opt.step()). Buckets are
        sized by ``comm_buffer_size``; inside ``no_sync()`` this is a
        no-op, mirroring the reference Reducer. The jitted TrainStep
        path needs none of this (GSPMD emits the fused all-reduce)."""
        if self._no_sync:
            return
        from .collectives import bucketed_allreduce_gradients
        params = list(parameters if parameters is not None
                      else self._layers.parameters())
        bucketed_allreduce_gradients(
            params, group=self._dp_group,
            bucket_bytes=self._comm_buffer_bytes)

    def no_sync(self):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            prev, self._no_sync = self._no_sync, True
            try:
                yield
            finally:
                self._no_sync = prev
        return ctx()

    def scale_loss(self, loss):
        return loss

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, state_dict, *a, **k):
        return self._layers.set_state_dict(state_dict, *a, **k)
