"""Thin paddle.distributed.* op/layer helpers that sit on top of the
mesh + mpu layers (reference: python/paddle/distributed/collective.py's
``split`` and python/paddle/distributed/auto_parallel/api.py's
``unshard_dtensor`` / ``shard_dataloader`` — verify)."""
from __future__ import annotations

import numpy as np


def split(x, size, operation="linear", axis=0, num_partitions=None,
          gather_out=True, weight_attr=None, bias_attr=None, name=None):
    """Model-parallel helper (reference: paddle.distributed.split):
    builds the partitioned layer for ``operation`` over the current
    mesh's mp axis and applies it to ``x``.

    - ``operation="linear"``: ``size=(in, out)``; axis=1 column-splits
      the weight (ColumnParallelLinear), axis=0 row-splits it
      (RowParallelLinear).
    - ``operation="embedding"``: ``size=(vocab, dim)``; the vocab dim
      shards (VocabParallelEmbedding).

    Note: each call BUILDS the layer (static-graph usage, as in the
    reference); imperative models should instantiate the
    fleet.meta_parallel layers once instead.
    """
    from .fleet.meta_parallel import (ColumnParallelLinear,
                                      RowParallelLinear,
                                      VocabParallelEmbedding)
    if operation == "linear":
        in_f, out_f = size
        if axis == 1:
            layer = ColumnParallelLinear(
                in_f, out_f, weight_attr=weight_attr,
                has_bias=bias_attr is not False,
                gather_output=gather_out)
        elif axis == 0:
            layer = RowParallelLinear(
                in_f, out_f, weight_attr=weight_attr,
                has_bias=bias_attr is not False,
                input_is_parallel=not gather_out)
        else:
            raise ValueError(f"linear split axis must be 0 or 1, got {axis}")
    elif operation == "embedding":
        vocab, dim = size
        layer = VocabParallelEmbedding(vocab, dim, weight_attr=weight_attr)
    else:
        raise ValueError(f"unsupported split operation {operation!r}")
    return layer(x)


def unshard_dtensor(dist_tensor):
    """Dist tensor → plain replicated Tensor with the full global value
    (reference: dist.unshard_dtensor). Partial placements are summed
    first (never silently dropped)."""
    from ..tensor import Tensor
    mesh = getattr(dist_tensor, "process_mesh", None)
    if mesh is None:
        return dist_tensor
    from .auto_parallel_api import Replicate, reshard
    ndim = len(mesh.shape)
    rep = reshard(dist_tensor, mesh, [Replicate() for _ in range(ndim)])
    out = Tensor(rep._dense_value(),
                 stop_gradient=dist_tensor.stop_gradient)
    return out


class _ShardDataloader:
    """Iterates a loader, placing each batch on ``mesh`` sharded along
    ``shard_dims`` (batch dim by default) — the input side of the
    semi-auto-parallel story (reference: dist.shard_dataloader)."""

    def __init__(self, dataloader, meshes, input_keys=None,
                 shard_dims=None, is_dataset_splitted=False):
        self._loader = dataloader
        self._mesh = meshes[0] if isinstance(meshes, (list, tuple)) \
            else meshes
        self._input_keys = input_keys
        # shard_dims: mesh axis name to shard the batch over (defaults
        # to the first mesh axis); None disables sharding (replicate)
        if shard_dims is None:
            shard_dims = self._mesh.dim_names[0] \
                if getattr(self._mesh, "dim_names", None) else None
        self._shard_dim = shard_dims

    def __len__(self):
        return len(self._loader)

    def _place(self, value):
        from .auto_parallel_api import Replicate, Shard, shard_tensor
        placements = []
        for name in self._mesh.dim_names:
            if name == self._shard_dim:
                placements.append(Shard(0))
            else:
                placements.append(Replicate())
        return shard_tensor(value, self._mesh, placements)

    def __iter__(self):
        for batch in self._loader:
            if isinstance(batch, dict):
                keys = self._input_keys or list(batch)
                yield {k: self._place(batch[k]) if k in keys else batch[k]
                       for k in batch}
            elif isinstance(batch, (list, tuple)):
                yield type(batch)(self._place(b) for b in batch)
            else:
                yield self._place(batch)


def shard_dataloader(dataloader, meshes, input_keys=None, shard_dims=None,
                     is_dataset_splitted=False):
    return _ShardDataloader(dataloader, meshes, input_keys, shard_dims,
                            is_dataset_splitted)
