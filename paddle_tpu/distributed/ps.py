"""Parameter-server mode for sparse models (reference:
python/paddle/distributed/ps/ + paddle/fluid/distributed/ps/ — the
brpc+rocksdb service with MemorySparseTable/SSDSparseTable, GeoSGD — verify).

TPU-native scope decision: the reference's PS is a ~150k-LoC CPU recsys
stack. Here PS mode is a compact, working equivalent for the same API
shape: in-memory sparse embedding tables sharded across server processes
(row → server by ``id % num_servers``), pull/push over the
:mod:`paddle_tpu.distributed.rpc` transport, server-side SGD/Adagrad, and
a ``SparseEmbedding`` layer whose backward pushes gradients via the
autograd grad-hook. Dense compute stays on the accelerator; only the
sparse rows live host-side — which is exactly the reference's split.

Training modes (reference: the ``Communicator`` family in
paddle/fluid/distributed/ps/service/communicator/ — verify):

- **sync** (default): every push blocks until the servers applied it.
- **async**: pushes are merged by id into a per-table pending buffer and
  flushed to the servers by a background thread — the trainer never
  blocks on the send (the reference's AsyncCommunicator merge+send
  queue). ``barrier_worker()`` drains the buffer.
- **geo** (GeoSGD): the trainer trains against a *local* copy of the
  touched rows (local SGD applied immediately), accumulating the delta
  vs the server copy; every ``geo_step`` pushes the accumulated deltas
  are shipped (servers *add* deltas — multi-trainer updates merge) and
  the local cache refreshes from the merged server state (the
  reference's GeoCommunicator).

Table types (reference: paddle/fluid/distributed/ps/table/ — verify):

- **memory** (default): every row lives in the server process's RAM
  (the reference's MemorySparseTable).
- **ssd**: hot rows in a bounded LRU cache, cold rows spilled to an
  embedded on-disk store — the reference's SSDSparseTable keeps its
  cold tier in rocksdb; here the stdlib's sqlite3 B-tree plays that
  role (no new dependency). Evictions write back row + optimizer
  state; reads fault rows back in transparently, so a table can be
  (much) larger than server RAM.

Roles follow the launch contract: ``TRAINING_ROLE`` = ``PSERVER`` |
``TRAINER``, ``PADDLE_PSERVER_NUM``, ``PADDLE_TRAINER_NUM``.
"""
from __future__ import annotations

import collections
import os
import sqlite3
import tempfile
import threading
import time
from typing import Optional

import numpy as np

from . import rpc
from ..utils.flags import env_int, env_str

__all__ = ["init_server", "run_server", "init_worker", "stop_worker",
           "create_table", "pull_sparse", "push_sparse", "save_table",
           "table_size", "table_stats", "SparseEmbedding", "is_server",
           "is_worker", "server_num", "worker_num", "shutdown",
           "barrier_worker", "training_mode", "set_training_mode"]


# ---------------------------------------------------------------------------
# server side: tables live in this process-global registry
# ---------------------------------------------------------------------------

class _SparseTable:
    """One shard of a sparse table: id → (row, per-row optimizer state).
    Rows materialize on first touch (the reference's lazy sparse init)."""

    def __init__(self, dim, init_range=0.01, optimizer="sgd", lr=0.1,
                 seed=0):
        self.dim = int(dim)
        self.init_range = float(init_range)
        self.optimizer = optimizer
        self.lr = float(lr)
        self.rows: dict[int, np.ndarray] = {}
        self.accum: dict[int, np.ndarray] = {}     # adagrad G
        self._rng = np.random.RandomState(seed)
        self._lock = threading.Lock()

    def _row(self, i: int) -> np.ndarray:
        r = self.rows.get(i)
        if r is None:
            r = self._rng.uniform(-self.init_range, self.init_range,
                                  self.dim).astype(np.float32)
            self.rows[i] = r
        return r

    def _acc(self, i: int) -> np.ndarray:
        acc = self.accum.get(i)
        if acc is None:
            acc = np.zeros(self.dim, np.float32)
            self.accum[i] = acc
        return acc

    def pull(self, ids) -> np.ndarray:
        with self._lock:
            return np.stack([self._row(int(i)) for i in ids])

    def push(self, ids, grads: np.ndarray):
        with self._lock:
            for i, g in zip(ids, grads):
                i = int(i)
                row = self._row(i)
                if self.optimizer == "adagrad":
                    acc = self._acc(i)
                    acc += g * g
                    row -= self.lr * g / (np.sqrt(acc) + 1e-8)
                else:                                   # sgd
                    row -= self.lr * g

    def size(self) -> int:
        with self._lock:
            return len(self.rows)

    def stats(self) -> dict:
        with self._lock:
            return {"type": "memory", "hot_rows": len(self.rows),
                    "disk_rows": 0, "cache_capacity": None, "path": None}

    def state(self):
        # deep-copy under the lock: the row arrays are mutated in place by
        # push, so sharing them would let a snapshot tear mid-update
        with self._lock:
            return {k: v.copy() for k, v in self.rows.items()}

    def snapshot(self):
        """(sorted ids, dense rows) for save — one dense array, no
        intermediate per-row dict/copies."""
        with self._lock:
            ids = np.array(sorted(self.rows), np.int64)
            out = np.empty((len(ids), self.dim), np.float32)
            for j, i in enumerate(ids):
                out[j] = self.rows[int(i)]
            return ids, out


class _SSDSparseTable(_SparseTable):
    """Disk-backed shard (reference SSDSparseTable,
    paddle/fluid/distributed/ps/table/ssd_sparse_table.* — verify: hot
    rows in a memory cache, cold tier in rocksdb). Here: a bounded LRU of
    hot entries over an embedded sqlite3 B-tree. Every cache entry is
    ``[row, adagrad_acc|None]``; eviction writes the pair back, a read
    miss faults it in, so in-place mutation by :meth:`push` /
    :func:`_srv_push_delta` is durable regardless of access pattern."""

    def __init__(self, dim, init_range=0.01, optimizer="sgd", lr=0.1,
                 seed=0, path=None, cache_rows=4096):
        super().__init__(dim, init_range, optimizer, lr, seed)
        self.cache_rows = max(1, int(cache_rows))
        self._owns_path = path is None
        self.path = path or os.path.join(
            tempfile.gettempdir(),
            f"pt_ps_ssd_{os.getpid()}_{id(self):x}.sqlite")
        # autocommit (isolation_level=None): evictions must not pin an
        # ever-growing implicit write transaction + rollback journal
        self._db = sqlite3.connect(self.path, check_same_thread=False,
                                   isolation_level=None)
        # the sqlite file is a spill tier, not the system of record for
        # durability (save_table is) — trade fsync for push throughput
        self._db.execute("PRAGMA journal_mode=MEMORY")
        self._db.execute("PRAGMA synchronous=OFF")
        self._db.execute("CREATE TABLE IF NOT EXISTS rows"
                         " (id INTEGER PRIMARY KEY, row BLOB, acc BLOB)")
        self._db.execute("CREATE TABLE IF NOT EXISTS meta"
                         " (k TEXT PRIMARY KEY, v INTEGER)")
        prev = self._db.execute(
            "SELECT v FROM meta WHERE k='dim'").fetchone()
        if prev is None:
            self._db.execute("INSERT INTO meta VALUES ('dim', ?)",
                             (self.dim,))
        elif int(prev[0]) != self.dim:
            # an explicit ssd_path warm-starts from the previous run's
            # rows — but only if the geometry matches
            raise ValueError(
                f"ssd table at {self.path} was created with dim "
                f"{int(prev[0])}, reopened with dim {self.dim}")
        self._hot: collections.OrderedDict[int, list] = \
            collections.OrderedDict()
        # ids initialized fresh and not yet written to disk: lets size()
        # count without flushing the whole hot cache
        self._fresh: set[int] = set()
        # the parent's dict storage is unused; poison it so any code that
        # still reaches for .rows fails loudly instead of silently
        # reading an empty table
        self.rows = None
        self.accum = None

    # storage --------------------------------------------------------------
    def _entry(self, i: int) -> list:
        e = self._hot.get(i)
        if e is not None:
            self._hot.move_to_end(i)
            return e
        cur = self._db.execute("SELECT row, acc FROM rows WHERE id=?",
                               (i,)).fetchone()
        if cur is None:
            row = self._rng.uniform(-self.init_range, self.init_range,
                                    self.dim).astype(np.float32)
            acc = None
            self._fresh.add(i)
        else:
            row = np.frombuffer(cur[0], np.float32).copy()
            acc = (np.frombuffer(cur[1], np.float32).copy()
                   if cur[1] is not None else None)
        e = [row, acc]
        self._hot[i] = e
        while len(self._hot) > self.cache_rows:
            old, (orow, oacc) = self._hot.popitem(last=False)
            self._write(old, orow, oacc)
        return e

    def _write(self, i, row, acc):
        self._db.execute(
            "INSERT OR REPLACE INTO rows (id, row, acc) VALUES (?,?,?)",
            (i, row.tobytes(), None if acc is None else acc.tobytes()))
        self._fresh.discard(i)

    def _row(self, i: int) -> np.ndarray:
        return self._entry(i)[0]

    def _acc(self, i: int) -> np.ndarray:
        e = self._entry(i)
        if e[1] is None:
            e[1] = np.zeros(self.dim, np.float32)
        return e[1]

    def _flush_locked(self):
        for i, (row, acc) in self._hot.items():
            self._write(i, row, acc)

    def flush(self):
        with self._lock:
            self._flush_locked()

    def _total_locked(self) -> int:
        # disk rows + hot rows that have never been written out; hot
        # rows faulted in from disk are already counted by the db
        return (self._db.execute("SELECT COUNT(*) FROM rows").fetchone()[0]
                + len(self._fresh))

    def size(self) -> int:
        with self._lock:
            return self._total_locked()

    def stats(self) -> dict:
        with self._lock:
            total = self._total_locked()
            return {"type": "ssd", "hot_rows": len(self._hot),
                    "disk_rows": total - len(self._hot),   # cold tier
                    "total_rows": total,
                    "cache_capacity": self.cache_rows,
                    "path": self.path}

    def state(self):
        with self._lock:
            self._flush_locked()
            return {int(i): np.frombuffer(b, np.float32).copy()
                    for i, b in self._db.execute(
                        "SELECT id, row FROM rows")}

    def snapshot(self):
        """Cursor-streamed (ids, rows) for save: one preallocated dense
        array filled straight from the sqlite cursor — the npz format
        needs the rows contiguous once, but nothing else is ever
        materialized (no per-row dict, no stack of copies)."""
        with self._lock:
            self._flush_locked()
            n = self._db.execute("SELECT COUNT(*) FROM rows").fetchone()[0]
            ids = np.empty(n, np.int64)
            out = np.empty((n, self.dim), np.float32)
            for j, (i, b) in enumerate(self._db.execute(
                    "SELECT id, row FROM rows ORDER BY id")):
                ids[j] = i
                out[j] = np.frombuffer(b, np.float32)
            return ids, out

    def close(self):
        """Close the spill store; default-path (temp) files are deleted —
        an explicit ``ssd_path`` is kept for warm starts."""
        with self._lock:
            if self._db is None:
                return
            if not self._owns_path:
                self._flush_locked()
            self._db.close()
            self._db = None
            if self._owns_path:
                try:
                    os.unlink(self.path)
                except OSError:
                    pass


_TABLES: dict[str, _SparseTable] = {}
_SERVER_STOP = threading.Event()


# module-level so they are picklable rpc targets ----------------------------

def _srv_create_table(name, dim, init_range, optimizer, lr, seed,
                      table_type="memory", cache_rows=4096, ssd_path=None):
    if name not in _TABLES:
        if table_type == "ssd":
            _TABLES[name] = _SSDSparseTable(
                dim, init_range, optimizer, lr, seed,
                path=ssd_path, cache_rows=cache_rows)
        else:
            _TABLES[name] = _SparseTable(dim, init_range, optimizer, lr,
                                         seed)
    return True


def _srv_pull(name, ids):
    return _TABLES[name].pull(ids)


def _srv_push(name, ids, grads):
    _TABLES[name].push(ids, grads)
    return True


def _srv_push_delta(name, ids, deltas):
    """GeoSGD merge: server ADDS the trainer's accumulated local delta
    (no server-side optimizer — the trainer already applied its lr) and
    returns the merged rows so the trainer can refresh its cache in the
    same round trip."""
    t = _TABLES[name]
    with t._lock:
        out = np.empty((len(ids), t.dim), np.float32)
        for j, (i, d) in enumerate(zip(ids, deltas)):
            row = t._row(int(i))
            row += d
            out[j] = row
    return out


def _srv_size(name):
    return _TABLES[name].size()


def _srv_stats(name):
    return _TABLES[name].stats()


def _srv_save(name, path):
    t = _TABLES[name]
    ids, rows = t.snapshot()
    np.savez(path, ids=ids, rows=rows)
    return len(ids)


def _srv_stop():
    # shutdown is the last rpc by contract — safe to tear down the
    # tables' spill stores here (temp-path sqlite files are unlinked)
    for t in _TABLES.values():
        close = getattr(t, "close", None)
        if close is not None:
            close()
    _TABLES.clear()
    _SERVER_STOP.set()
    return True


# ---------------------------------------------------------------------------
# worker-side communicators (async / geo modes)
# ---------------------------------------------------------------------------

class _AsyncCommunicator:
    """Merge-and-send queue: pushes accumulate by id in a pending buffer;
    a daemon thread flushes it to the servers every ``interval`` seconds
    (reference AsyncCommunicator: merge_sparse_grad + send thread)."""

    def __init__(self, interval=0.02):
        self._pending: dict[str, dict[int, np.ndarray]] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._interval = interval
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def push(self, name, ids, grads):
        with self._lock:
            tab = self._pending.setdefault(name, {})
            for i, g in zip(ids, grads):
                i = int(i)
                cur = tab.get(i)
                tab[i] = g.copy() if cur is None else cur + g

    def _drain(self):
        with self._lock:
            pending, self._pending = self._pending, {}
        for name, tab in pending.items():
            if not tab:
                continue
            ids = np.fromiter(tab, np.int64, len(tab))
            grads = np.stack([tab[int(i)] for i in ids])
            _push_sparse_sync(name, ids, grads)

    def _loop(self):
        while not self._stop.is_set():
            self._stop.wait(self._interval)
            try:
                self._drain()
            except Exception:
                if self._stop.is_set():   # rpc torn down mid-flush
                    break
                raise

    def flush(self):
        self._drain()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)
        self._drain()


class _GeoCommunicator:
    """GeoSGD: local rows + accumulated deltas, periodic merge.

    ``pull`` serves from the local cache (filling misses from the
    servers), ``push`` applies plain SGD *locally* and records the delta;
    every ``geo_step`` pushes the deltas ship to the servers (which add
    them) and the touched rows refresh to the merged global state."""

    def __init__(self, geo_step=100):
        self.geo_step = int(geo_step)
        self._cache: dict[str, dict[int, np.ndarray]] = {}
        self._delta: dict[str, dict[int, np.ndarray]] = {}
        self._lock = threading.Lock()
        self._pushes = 0

    def pull(self, name, flat_ids):
        cache = self._cache.setdefault(name, {})
        with self._lock:
            missing = np.array(
                [i for i in dict.fromkeys(int(x) for x in flat_ids)
                 if i not in cache], np.int64)
        if missing.size:
            rows = _pull_sparse_sync(name, missing)
            with self._lock:
                for i, r in zip(missing, rows):
                    cache.setdefault(int(i), r.copy())
        with self._lock:
            return np.stack([cache[int(i)] for i in flat_ids])

    def push(self, name, ids, grads):
        lr = _TABLE_META.get(name, {}).get("lr", 0.1)
        cache = self._cache.setdefault(name, {})
        delta = self._delta.setdefault(name, {})
        with self._lock:
            for i, g in zip(ids, grads):
                i = int(i)
                upd = (-lr * g).astype(np.float32)
                row = cache.get(i)
                if row is None:       # pushed before ever pulled
                    row = _pull_sparse_sync(name, np.array([i]))[0]
                    cache[i] = row
                row += upd
                cur = delta.get(i)
                delta[i] = upd if cur is None else cur + upd
            self._pushes += 1
            due = self._pushes % self.geo_step == 0
        if due:
            self.flush()

    def flush(self):
        with self._lock:
            deltas, self._delta = self._delta, {}
        for name, tab in deltas.items():
            if not tab:
                continue
            ids = np.fromiter(tab, np.int64, len(tab))
            ds = np.stack([tab[int(i)] for i in ids])
            merged = _push_delta_sync(name, ids, ds)
            with self._lock:
                cache = self._cache.setdefault(name, {})
                for i, r in zip(ids, merged):
                    cache[int(i)] = r.copy()

    def stop(self):
        self.flush()


_MODE = "sync"
_COMM: Optional[object] = None
_TABLE_META: dict[str, dict] = {}


def training_mode() -> str:
    """The worker's active PS mode: "sync" | "async" | "geo"."""
    return _MODE


def set_training_mode(mode: str, geo_step: int = 100,
                      async_interval: float = 0.02):
    """Switch the worker's communicator (drains the old one first).
    Normally chosen once via :func:`init_worker`; exposed so a trainer
    can e.g. fall back to sync pushes before an evaluation pass."""
    global _MODE, _COMM
    if mode not in ("sync", "async", "geo"):
        raise ValueError(f"unknown PS mode {mode!r}")
    if _COMM is not None:
        _COMM.stop()
        _COMM = None
    _MODE = mode
    if mode == "async":
        _COMM = _AsyncCommunicator(interval=async_interval)
    elif mode == "geo":
        _COMM = _GeoCommunicator(geo_step=geo_step)


def barrier_worker():
    """Drain any pending async/geo sends (reference
    fleet.barrier_worker before save/evaluate)."""
    if _COMM is not None:
        _COMM.flush()


# ---------------------------------------------------------------------------
# role plumbing
# ---------------------------------------------------------------------------

def is_server() -> bool:
    return env_str("TRAINING_ROLE", "TRAINER").upper() == "PSERVER"


def is_worker() -> bool:
    return not is_server()


def server_num() -> int:
    return env_int("PADDLE_PSERVER_NUM", 1)


def worker_num() -> int:
    return env_int("PADDLE_TRAINER_NUM", 1)


def _rpc_world():
    return server_num() + worker_num()


def _server_name(i):
    return f"ps:{i}"


def _join(name, role_idx, as_server):
    """Common join path: compute the global rpc rank from the role index
    and align the store env (PADDLE_TRAINER_ID/NUM name the *rpc* world
    from here on — PS processes do not use the collective path)."""
    rank = role_idx if as_server else server_num() + role_idx
    world = _rpc_world()
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(world)
    rpc.init_rpc(name, rank=rank, world_size=world)


def init_server(name: Optional[str] = None):
    """Join the PS cluster as a server (reference fleet.init_server)."""
    idx = env_int("PADDLE_TRAINER_ID", 0)
    _join(name or _server_name(idx), idx, as_server=True)


def run_server(poll_s=0.1):
    """Serve until a trainer calls :func:`shutdown` (fleet.run_server)."""
    while not _SERVER_STOP.is_set():
        time.sleep(poll_s)
    rpc.shutdown()


def init_worker(name: Optional[str] = None, mode: str = "sync",
                geo_step: int = 100, async_interval: float = 0.02):
    """Join the PS cluster as a trainer (reference fleet.init_worker).

    ``mode`` selects the communicator: "sync" (blocking pushes),
    "async" (merge+background-send), or "geo" (GeoSGD local training
    with delta sync every ``geo_step`` pushes)."""
    idx = env_int("PADDLE_TRAINER_ID", 0)
    _join(name or f"trainer:{idx}", idx, as_server=False)
    set_training_mode(mode, geo_step=geo_step,
                      async_interval=async_interval)


def stop_worker():
    global _COMM, _MODE
    if _COMM is not None:
        _COMM.stop()
        _COMM = None
    _MODE = "sync"
    rpc.shutdown()


def shutdown():
    """Trainer-side: stop every server, then leave the rpc world."""
    global _COMM, _MODE
    if _COMM is not None:
        _COMM.stop()
        _COMM = None
    _MODE = "sync"
    for s in range(server_num()):
        try:
            rpc.rpc_sync(_server_name(s), _srv_stop, timeout=10)
        except Exception:
            pass
    rpc.shutdown()


# ---------------------------------------------------------------------------
# client API
# ---------------------------------------------------------------------------

def _shard(ids: np.ndarray):
    """Partition ids by owning server; returns {server_idx: positions}."""
    owners = ids % server_num()
    return {s: np.nonzero(owners == s)[0] for s in range(server_num())
            if (owners == s).any()}


def create_table(name, dim, init_range=0.01, optimizer="sgd", lr=0.1,
                 seed=0, table_type="memory", cache_rows=4096,
                 ssd_path=None):
    """Create ``name`` on every server shard (idempotent).

    ``table_type="ssd"`` selects the disk-spilling table: each shard
    keeps at most ``cache_rows`` rows hot in RAM and writes the rest to
    ``ssd_path + ".shard<s>"`` (a server-local temp file when unset) —
    the reference's SSDSparseTable tiering."""
    _TABLE_META[name] = {"dim": int(dim), "lr": float(lr),
                         "optimizer": optimizer, "type": table_type}
    futs = [rpc.rpc_async(_server_name(s), _srv_create_table,
                          args=(name, dim, init_range, optimizer, lr,
                                seed + s, table_type, cache_rows,
                                f"{ssd_path}.shard{s}" if ssd_path
                                else None), timeout=60)
            for s in range(server_num())]
    for f in futs:
        f.wait(65)


def _pull_sparse_sync(name, flat) -> np.ndarray:
    out = None
    shards = _shard(flat)
    futs = {s: rpc.rpc_async(_server_name(s), _srv_pull,
                             args=(name, flat[pos]), timeout=60)
            for s, pos in shards.items()}
    for s, fut in futs.items():
        rows = fut.wait(65)
        if out is None:
            out = np.zeros((flat.size, rows.shape[-1]), np.float32)
        out[shards[s]] = rows
    if out is None:
        raise ValueError("pull_sparse with empty ids")
    return out


def pull_sparse(name, ids) -> np.ndarray:
    """Fetch rows for ``ids`` (any shape) → array of shape ids.shape+(dim,).
    Fan-out to owning servers runs concurrently. In geo mode, rows come
    from the trainer's local GeoSGD cache (local updates visible)."""
    ids = np.asarray(ids, np.int64)
    flat = ids.reshape(-1)
    if _MODE == "geo" and _COMM is not None:
        out = _COMM.pull(name, flat)
    else:
        out = _pull_sparse_sync(name, flat)
    return out.reshape(ids.shape + (out.shape[-1],))


def _merge_by_id(ids, grads):
    ids = np.asarray(ids, np.int64).reshape(-1)
    grads = np.asarray(grads, np.float32).reshape(ids.size, -1)
    uniq, inv = np.unique(ids, return_inverse=True)
    merged = np.zeros((uniq.size, grads.shape[1]), np.float32)
    np.add.at(merged, inv, grads)
    return uniq, merged


def _push_sparse_sync(name, uniq, merged):
    futs = [rpc.rpc_async(_server_name(s), _srv_push,
                          args=(name, uniq[pos], merged[pos]), timeout=60)
            for s, pos in _shard(uniq).items()]
    for f in futs:
        f.wait(65)


def _push_delta_sync(name, ids, deltas) -> np.ndarray:
    """Ship GeoSGD deltas; returns merged rows in input order."""
    out = np.empty((ids.size, deltas.shape[1]), np.float32)
    shards = _shard(ids)
    futs = {s: rpc.rpc_async(_server_name(s), _srv_push_delta,
                             args=(name, ids[pos], deltas[pos]),
                             timeout=60)
            for s, pos in shards.items()}
    for s, fut in futs.items():
        out[shards[s]] = fut.wait(65)
    return out


def push_sparse(name, ids, grads):
    """Apply gradients to rows of ``ids``; duplicate ids within the batch
    are pre-summed host-side (the reference merges by key in the worker).
    Routing: sync → blocking server update; async → merge into the
    background send buffer; geo → local SGD + delta accumulation."""
    uniq, merged = _merge_by_id(ids, grads)
    if _COMM is not None and _MODE in ("async", "geo"):
        _COMM.push(name, uniq, merged)
    else:
        _push_sparse_sync(name, uniq, merged)


def table_size(name) -> int:
    return sum(rpc.rpc_sync(_server_name(s), _srv_size, args=(name,))
               for s in range(server_num()))


def table_stats(name) -> list:
    """Per-shard storage stats: ``[{type, hot_rows, disk_rows,
    cache_capacity, path}, ...]`` (one dict per server). For ssd tables
    ``disk_rows`` counts the spilled cold tier."""
    return [rpc.rpc_sync(_server_name(s), _srv_stats, args=(name,))
            for s in range(server_num())]


def save_table(name, dirname) -> int:
    os.makedirs(dirname, exist_ok=True)
    return sum(rpc.rpc_sync(_server_name(s), _srv_save,
                            args=(name, os.path.join(
                                dirname, f"{name}.shard{s}.npz")))
               for s in range(server_num()))


# ---------------------------------------------------------------------------
# model-side layer
# ---------------------------------------------------------------------------

class SparseEmbedding:
    """Embedding whose table lives on the parameter servers (reference:
    paddle.static.nn.sparse_embedding / DistributedLookupTable — verify).

    Forward pulls the touched rows into a leaf tensor; a grad hook on that
    leaf pushes the gradient back — so a normal ``loss.backward()``
    performs the PS update with no optimizer involvement (the server owns
    the optimizer, as in the reference)."""

    def __init__(self, name, num_embeddings, embedding_dim, optimizer="sgd",
                 lr=0.1, init_range=0.01, table_type="memory",
                 cache_rows=4096):
        self.table_name = name
        self.dim = int(embedding_dim)
        create_table(name, embedding_dim, init_range, optimizer, lr,
                     table_type=table_type, cache_rows=cache_rows)

    def __call__(self, ids):
        from ..tensor import Tensor, to_tensor
        ids_np = np.asarray(
            ids._value if isinstance(ids, Tensor) else ids, np.int64)
        uniq, inv = np.unique(ids_np.reshape(-1), return_inverse=True)
        rows = to_tensor(pull_sparse(self.table_name, uniq))
        rows.stop_gradient = False
        name = self.table_name

        def push_hook(grad):
            push_sparse(name, uniq, np.asarray(grad._value))
            return grad
        rows.register_hook(push_hook)
        from .. import ops
        flat = ops.gather(rows, to_tensor(inv.astype(np.int32)))
        return ops.reshape(flat, list(ids_np.shape) + [self.dim])
