"""Parameter-server mode for sparse models (reference:
python/paddle/distributed/ps/ + paddle/fluid/distributed/ps/ — the
brpc+rocksdb service with MemorySparseTable/SSDSparseTable, GeoSGD — verify).

TPU-native scope decision: the reference's PS is a ~150k-LoC CPU recsys
stack. Here PS mode is a compact, working equivalent for the same API
shape: in-memory sparse embedding tables sharded across server processes
(row → server by ``id % num_servers``), pull/push over the
:mod:`paddle_tpu.distributed.rpc` transport, server-side SGD/Adagrad, and
a ``SparseEmbedding`` layer whose backward pushes gradients via the
autograd grad-hook. Dense compute stays on the accelerator; only the
sparse rows live host-side — which is exactly the reference's split.
SSD/rocksdb spill and GeoSGD are out of scope (documented in README).

Roles follow the launch contract: ``TRAINING_ROLE`` = ``PSERVER`` |
``TRAINER``, ``PADDLE_PSERVER_NUM``, ``PADDLE_TRAINER_NUM``.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Optional

import numpy as np

from . import rpc

__all__ = ["init_server", "run_server", "init_worker", "stop_worker",
           "create_table", "pull_sparse", "push_sparse", "save_table",
           "table_size", "SparseEmbedding", "is_server", "is_worker",
           "server_num", "worker_num", "shutdown"]


# ---------------------------------------------------------------------------
# server side: tables live in this process-global registry
# ---------------------------------------------------------------------------

class _SparseTable:
    """One shard of a sparse table: id → (row, per-row optimizer state).
    Rows materialize on first touch (the reference's lazy sparse init)."""

    def __init__(self, dim, init_range=0.01, optimizer="sgd", lr=0.1,
                 seed=0):
        self.dim = int(dim)
        self.init_range = float(init_range)
        self.optimizer = optimizer
        self.lr = float(lr)
        self.rows: dict[int, np.ndarray] = {}
        self.accum: dict[int, np.ndarray] = {}     # adagrad G
        self._rng = np.random.RandomState(seed)
        self._lock = threading.Lock()

    def _row(self, i: int) -> np.ndarray:
        r = self.rows.get(i)
        if r is None:
            r = self._rng.uniform(-self.init_range, self.init_range,
                                  self.dim).astype(np.float32)
            self.rows[i] = r
        return r

    def pull(self, ids) -> np.ndarray:
        with self._lock:
            return np.stack([self._row(int(i)) for i in ids])

    def push(self, ids, grads: np.ndarray):
        with self._lock:
            for i, g in zip(ids, grads):
                i = int(i)
                row = self._row(i)
                if self.optimizer == "adagrad":
                    acc = self.accum.setdefault(
                        i, np.zeros(self.dim, np.float32))
                    acc += g * g
                    row -= self.lr * g / (np.sqrt(acc) + 1e-8)
                else:                                   # sgd
                    row -= self.lr * g

    def state(self):
        # deep-copy under the lock: the row arrays are mutated in place by
        # push, so sharing them would let a snapshot tear mid-update
        with self._lock:
            return {k: v.copy() for k, v in self.rows.items()}


_TABLES: dict[str, _SparseTable] = {}
_SERVER_STOP = threading.Event()


# module-level so they are picklable rpc targets ----------------------------

def _srv_create_table(name, dim, init_range, optimizer, lr, seed):
    if name not in _TABLES:
        _TABLES[name] = _SparseTable(dim, init_range, optimizer, lr, seed)
    return True


def _srv_pull(name, ids):
    return _TABLES[name].pull(ids)


def _srv_push(name, ids, grads):
    _TABLES[name].push(ids, grads)
    return True


def _srv_size(name):
    return len(_TABLES[name].rows)


def _srv_save(name, path):
    t = _TABLES[name]
    rows = t.state()
    ids = np.array(sorted(rows), np.int64)
    np.savez(path, ids=ids,
             rows=np.stack([rows[int(i)] for i in ids]) if len(ids)
             else np.zeros((0, t.dim), np.float32))
    return len(ids)


def _srv_stop():
    _SERVER_STOP.set()
    return True


# ---------------------------------------------------------------------------
# role plumbing
# ---------------------------------------------------------------------------

def is_server() -> bool:
    return os.environ.get("TRAINING_ROLE", "TRAINER").upper() == "PSERVER"


def is_worker() -> bool:
    return not is_server()


def server_num() -> int:
    return int(os.environ.get("PADDLE_PSERVER_NUM", 1))


def worker_num() -> int:
    return int(os.environ.get("PADDLE_TRAINER_NUM", 1))


def _rpc_world():
    return server_num() + worker_num()


def _server_name(i):
    return f"ps:{i}"


def _join(name, role_idx, as_server):
    """Common join path: compute the global rpc rank from the role index
    and align the store env (PADDLE_TRAINER_ID/NUM name the *rpc* world
    from here on — PS processes do not use the collective path)."""
    rank = role_idx if as_server else server_num() + role_idx
    world = _rpc_world()
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(world)
    rpc.init_rpc(name, rank=rank, world_size=world)


def init_server(name: Optional[str] = None):
    """Join the PS cluster as a server (reference fleet.init_server)."""
    idx = int(os.environ.get("PADDLE_TRAINER_ID", 0))
    _join(name or _server_name(idx), idx, as_server=True)


def run_server(poll_s=0.1):
    """Serve until a trainer calls :func:`shutdown` (fleet.run_server)."""
    while not _SERVER_STOP.is_set():
        time.sleep(poll_s)
    rpc.shutdown()


def init_worker(name: Optional[str] = None):
    """Join the PS cluster as a trainer (reference fleet.init_worker)."""
    idx = int(os.environ.get("PADDLE_TRAINER_ID", 0))
    _join(name or f"trainer:{idx}", idx, as_server=False)


def stop_worker():
    rpc.shutdown()


def shutdown():
    """Trainer-side: stop every server, then leave the rpc world."""
    for s in range(server_num()):
        try:
            rpc.rpc_sync(_server_name(s), _srv_stop, timeout=10)
        except Exception:
            pass
    rpc.shutdown()


# ---------------------------------------------------------------------------
# client API
# ---------------------------------------------------------------------------

def _shard(ids: np.ndarray):
    """Partition ids by owning server; returns {server_idx: positions}."""
    owners = ids % server_num()
    return {s: np.nonzero(owners == s)[0] for s in range(server_num())
            if (owners == s).any()}


def create_table(name, dim, init_range=0.01, optimizer="sgd", lr=0.1,
                 seed=0):
    """Create ``name`` on every server shard (idempotent)."""
    futs = [rpc.rpc_async(_server_name(s), _srv_create_table,
                          args=(name, dim, init_range, optimizer, lr,
                                seed + s), timeout=60)
            for s in range(server_num())]
    for f in futs:
        f.wait(65)


def pull_sparse(name, ids) -> np.ndarray:
    """Fetch rows for ``ids`` (any shape) → array of shape ids.shape+(dim,).
    Fan-out to owning servers runs concurrently."""
    ids = np.asarray(ids, np.int64)
    flat = ids.reshape(-1)
    out = None
    shards = _shard(flat)
    futs = {s: rpc.rpc_async(_server_name(s), _srv_pull,
                             args=(name, flat[pos]), timeout=60)
            for s, pos in shards.items()}
    for s, fut in futs.items():
        rows = fut.wait(65)
        if out is None:
            out = np.zeros((flat.size, rows.shape[-1]), np.float32)
        out[shards[s]] = rows
    if out is None:
        raise ValueError("pull_sparse with empty ids")
    return out.reshape(ids.shape + (out.shape[-1],))


def push_sparse(name, ids, grads):
    """Apply gradients to rows of ``ids``; duplicate ids within the batch
    are pre-summed host-side (the reference merges by key in the worker)."""
    ids = np.asarray(ids, np.int64).reshape(-1)
    grads = np.asarray(grads, np.float32).reshape(ids.size, -1)
    uniq, inv = np.unique(ids, return_inverse=True)
    merged = np.zeros((uniq.size, grads.shape[1]), np.float32)
    np.add.at(merged, inv, grads)
    futs = [rpc.rpc_async(_server_name(s), _srv_push,
                          args=(name, uniq[pos], merged[pos]), timeout=60)
            for s, pos in _shard(uniq).items()]
    for f in futs:
        f.wait(65)


def table_size(name) -> int:
    return sum(rpc.rpc_sync(_server_name(s), _srv_size, args=(name,))
               for s in range(server_num()))


def save_table(name, dirname) -> int:
    os.makedirs(dirname, exist_ok=True)
    return sum(rpc.rpc_sync(_server_name(s), _srv_save,
                            args=(name, os.path.join(
                                dirname, f"{name}.shard{s}.npz")))
               for s in range(server_num()))


# ---------------------------------------------------------------------------
# model-side layer
# ---------------------------------------------------------------------------

class SparseEmbedding:
    """Embedding whose table lives on the parameter servers (reference:
    paddle.static.nn.sparse_embedding / DistributedLookupTable — verify).

    Forward pulls the touched rows into a leaf tensor; a grad hook on that
    leaf pushes the gradient back — so a normal ``loss.backward()``
    performs the PS update with no optimizer involvement (the server owns
    the optimizer, as in the reference)."""

    def __init__(self, name, num_embeddings, embedding_dim, optimizer="sgd",
                 lr=0.1, init_range=0.01):
        self.table_name = name
        self.dim = int(embedding_dim)
        create_table(name, embedding_dim, init_range, optimizer, lr)

    def __call__(self, ids):
        from ..tensor import Tensor, to_tensor
        ids_np = np.asarray(
            ids._value if isinstance(ids, Tensor) else ids, np.int64)
        uniq, inv = np.unique(ids_np.reshape(-1), return_inverse=True)
        rows = to_tensor(pull_sparse(self.table_name, uniq))
        rows.stop_gradient = False
        name = self.table_name

        def push_hook(grad):
            push_sparse(name, uniq, np.asarray(grad._value))
            return grad
        rows.register_hook(push_hook)
        from .. import ops
        flat = ops.gather(rows, to_tensor(inv.astype(np.int32)))
        return ops.reshape(flat, list(ids_np.shape) + [self.dim])
