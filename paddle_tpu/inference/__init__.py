"""Inference deployment (``paddle.inference`` parity).

Reference parity: paddle/fluid/inference/ — AnalysisConfig +
AnalysisPredictor + zero-copy tensors (paddle/fluid/inference/api/
analysis_predictor.cc, paddle_inference_api.h — verify).

TPU-native design: "analysis passes + saved program" becomes AOT
compilation — the model is traced once, exported as serialized
StableHLO (jax.export) with weights stored alongside, and the
predictor executes the compiled artifact. XLA does the reference's
fusion/quant passes at compile time; TensorRT-subgraph offload has no
TPU analog (XLA *is* the whole-graph compiler)."""
from __future__ import annotations

import os
import pickle
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "export_model",
           "convert_to_predictor", "PrecisionType", "export_decoder",
           "GenerationPredictor"]


class PrecisionType:
    Float32 = "float32"
    Bfloat16 = "bfloat16"
    Half = "float16"
    Int8 = "int8"


class Config:
    """AnalysisConfig analog. IR/memory switches are accepted for API
    parity; XLA already performs those optimizations."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        self.model_path = prog_file
        self.params_path = params_file
        self._precision = PrecisionType.Float32
        self._device = None
        self._glog_info = True
        self._memory_optim = True
        self._ir_optim = True

    def set_model(self, prog_file, params_file=None):
        self.model_path = prog_file
        self.params_path = params_file

    def set_prog_file(self, path):
        self.model_path = path

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = f"tpu:{device_id}"  # gpu calls map to the TPU chip

    def disable_gpu(self):
        self._device = "cpu"

    def enable_memory_optim(self, flag=True):
        self._memory_optim = flag

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def set_cpu_math_library_num_threads(self, n):
        pass

    def disable_glog_info(self):
        self._glog_info = False

    def precision(self):
        return self._precision


class _IOHandle:
    """Zero-copy-style tensor handle (paddle_infer.Tensor analog)."""

    def __init__(self, name: str, spec: jax.ShapeDtypeStruct):
        self.name = name
        self._spec = spec
        self._value = None

    def shape(self):
        return list(self._spec.shape)

    def copy_from_cpu(self, arr: np.ndarray):
        self._value = jnp.asarray(arr)

    def share_external_data(self, arr):
        self._value = arr if isinstance(arr, jax.Array) else \
            jnp.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._value)


def export_model(layer, input_spec: Sequence, path: str):
    """Trace + AOT-export a Layer: serialized StableHLO with weights.
    ``input_spec``: static.InputSpec / Tensor / ndarray examples."""
    from ..nn import Layer
    from ..static import InputSpec
    from ..tensor import Tensor
    from .. import framework

    _sym_count = [0]
    _scope = [None]  # ONE scope for the whole export: symbolic dims from
    #                  different scopes cannot be mixed in one program

    def _shape(dims):
        """-1/None dims (InputSpec dynamic axes) become jax.export
        symbolic dimensions, so one exported program serves any size on
        that axis — the reference's dynamic-shape ProgramDesc export."""
        out = []
        for d in dims:
            if d is None or (isinstance(d, int) and d < 0):
                if _scope[0] is None:
                    _scope[0] = jax.export.SymbolicScope()
                _sym_count[0] += 1
                out.append(jax.export.symbolic_shape(
                    f"_dyn{_sym_count[0]}", scope=_scope[0])[0])
            else:
                out.append(int(d))
        return tuple(out)

    def to_sds(s):
        if isinstance(s, InputSpec):
            return jax.ShapeDtypeStruct(_shape(s.shape),
                                        framework.convert_dtype(s.dtype))
        if isinstance(s, Tensor):
            return jax.ShapeDtypeStruct(tuple(s.shape),
                                        s._value.dtype)
        arr = np.asarray(s)
        return jax.ShapeDtypeStruct(arr.shape, arr.dtype)

    specs = [to_sds(s) for s in input_spec]
    ptensors = dict(layer.named_parameters())
    btensors = dict(layer.named_buffers())
    pvals = {k: t._value for k, t in ptensors.items()}
    bvals = {k: t._value for k, t in btensors.items()}

    def fn(pv, bv, *inputs):
        saved = [(t, t._value) for t in
                 list(ptensors.values()) + list(btensors.values())]
        try:
            for k, v in pv.items():
                ptensors[k]._value = v
            for k, v in bv.items():
                btensors[k]._value = v
            was_training = layer.training
            layer.eval()
            try:
                with framework.functional_mode(), framework.rng_context(
                        jax.random.PRNGKey(0)):
                    out = layer(*[Tensor(x) for x in inputs])
            finally:
                if was_training:
                    layer.train()
            return jax.tree_util.tree_map(
                lambda o: o._value if isinstance(o, Tensor) else o, out,
                is_leaf=lambda o: isinstance(o, Tensor))
        finally:
            for t, v in saved:
                t._value = v

    pspecs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
              for k, v in pvals.items()}
    bspecs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
              for k, v in bvals.items()}
    exported = jax.export.export(jax.jit(fn))(pspecs, bspecs, *specs)
    blob = {
        "stablehlo": exported.serialize(),
        "params": {k: np.asarray(v) for k, v in pvals.items()},
        "buffers": {k: np.asarray(v) for k, v in bvals.items()},
        "input_specs": [(tuple(d if isinstance(d, int) else -1
                               for d in s.shape), str(s.dtype))
                        for s in specs],
        "input_names": [f"x{i}" for i in range(len(specs))],
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump(blob, f, protocol=pickle.HIGHEST_PROTOCOL)
    return path + ".pdmodel"


class Predictor:
    """AnalysisPredictor analog over a serialized StableHLO artifact."""

    def __init__(self, config: Config):
        self.config = config
        path = config.model_path
        if path is None:
            raise ValueError("Config.set_model(path) before "
                             "create_predictor")
        if not path.endswith(".pdmodel"):
            path = path + ".pdmodel"
        with open(path, "rb") as f:
            blob = pickle.load(f)
        self._exported = jax.export.deserialize(blob["stablehlo"])
        self._params = {k: jnp.asarray(v)
                        for k, v in blob["params"].items()}
        self._buffers = {k: jnp.asarray(v)
                         for k, v in blob["buffers"].items()}
        self._input_names: List[str] = blob["input_names"]
        self._input_specs = [
            jax.ShapeDtypeStruct(shape, np.dtype(dtype))
            for shape, dtype in blob["input_specs"]]
        self._inputs: Dict[str, _IOHandle] = {
            n: _IOHandle(n, s)
            for n, s in zip(self._input_names, self._input_specs)}
        self._outputs: List = []

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name) -> _IOHandle:
        return self._inputs[name]

    def run_on_device(self, args: Sequence):
        """Zero-copy path: device (or jnp-convertible) inputs in, device
        arrays out — no host round trip (used by jit.TranslatedLayer)."""
        out = self._exported.call(self._params, self._buffers,
                                  *[jnp.asarray(a) for a in args])
        self._outputs = list(out) if isinstance(out, (tuple, list)) \
            else [out]
        return self._outputs

    def run(self, inputs: Optional[Sequence[np.ndarray]] = None):
        if inputs is not None:
            for n, arr in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(np.asarray(arr))
        args = [self._inputs[n]._value for n in self._input_names]
        if any(a is None for a in args):
            missing = [n for n in self._input_names
                       if self._inputs[n]._value is None]
            raise RuntimeError(f"inputs not set: {missing}")
        self.run_on_device(args)
        if inputs is not None:
            return [np.asarray(o) for o in self._outputs]
        return None

    def get_output_names(self):
        return [f"out{i}" for i in range(len(self._outputs))]

    def get_output_handle(self, name) -> _IOHandle:
        i = int(name.replace("out", "") or 0)
        h = _IOHandle(name, jax.ShapeDtypeStruct(
            self._outputs[i].shape, self._outputs[i].dtype))
        h._value = self._outputs[i]
        return h


def export_decoder(model, path: str, batch: int, prompt_len: int,
                   max_len: int, temperature: float = 0.0,
                   top_k: int = 0, top_p: float = 1.0,
                   engine_slots: Optional[int] = None,
                   engine_decode_block: int = 8,
                   engine_prompt_buckets: Sequence[int] = (16, 32),
                   engine_paged: bool = False,
                   engine_block_size: int = 16,
                   engine_num_blocks: Optional[int] = None,
                   engine_prefill_chunk: Optional[int] = None):
    """AOT-export the autoregressive serving path of a causal LM: TWO
    StableHLO programs — prefill (prompt → first token + KV cache) and
    decode step (token, cache, pos → next token, cache) — plus weights
    (reference: AnalysisPredictor serving autoregressive models,
    SURVEY §3.5; the decode loop then runs without Python tracing).

    The model must implement ``init_kv_cache`` and a cached ``forward``
    (see models/generation.GenerationMixin). The SAME pure step function
    as GenerationMixin.generate is exported twice — once specialized to
    the prompt block at pos=0 (prefill, cache zero-initialized inside),
    once to a single token — so in-process and served decoding share one
    implementation.

    ``engine_slots``: additionally export the continuous-batching
    engine's programs (the slot-pool decode block over
    ``engine_slots`` × ``max_len`` caches, plus one prefill per prompt
    bucket) so ``GenerationPredictor.serve()`` runs the SAME serving
    engine from the artifact alone — see ``paddle_tpu.serving``.

    ``engine_paged=True`` exports the PAGED engine's two programs
    instead: the block-arena decode block (in-state block tables) and
    the ONE chunked-prefill chunk program — ``engine_block_size`` /
    ``engine_num_blocks`` / ``engine_prefill_chunk`` mirror the
    ``PagedEngine`` knobs (defaults match: full dense capacity + trash
    block, chunk = 2 blocks). The artifact records the program arities
    (``block_outputs``/``chunk_outputs``) so a serving host can tell
    what it loaded; ``serving.paging.PagedArtifactStepBackend`` is the
    loader. The int8 KV arena is not exported (fp32 arena only)."""
    from ..models.generation import build_decode_step
    from ..tensor import Tensor

    sample_kwargs = dict(temperature=temperature, top_k=top_k,
                         top_p=top_p)
    pvals = [p._value for _, p in model.named_parameters()]
    bvals = [b._value for _, b in model.named_buffers()]
    cache0 = model.init_kv_cache(batch, max_len)
    flat0, tree = jax.tree.flatten(
        cache0, is_leaf=lambda x: isinstance(x, Tensor))
    cache_specs = tuple(jax.ShapeDtypeStruct(c._value.shape,
                                             c._value.dtype)
                        for c in flat0)
    tree_holder = {"tree": tree}
    step = build_decode_step(model, sample_kwargs, tree_holder)

    def prefill(pv, bv, ids, key):
        zero_cache = tuple(jnp.zeros(s.shape, s.dtype)
                           for s in cache_specs)
        return step(pv, bv, ids, zero_cache,
                    jnp.asarray(0, jnp.int32), key)

    pspecs = [jax.ShapeDtypeStruct(v.shape, v.dtype) for v in pvals]
    bspecs = [jax.ShapeDtypeStruct(v.shape, v.dtype) for v in bvals]
    ids_spec = jax.ShapeDtypeStruct((batch, prompt_len), jnp.int32)
    tok_spec = jax.ShapeDtypeStruct((batch, 1), jnp.int32)
    pos_spec = jax.ShapeDtypeStruct((), jnp.int32)
    key_spec = jax.ShapeDtypeStruct((2,), jnp.uint32)
    exp_prefill = jax.export.export(jax.jit(prefill))(
        pspecs, bspecs, ids_spec, key_spec)
    exp_step = jax.export.export(jax.jit(step))(
        pspecs, bspecs, tok_spec, cache_specs, pos_spec, key_spec)
    blob = {
        "prefill": exp_prefill.serialize(),
        "step": exp_step.serialize(),
        "params": [np.asarray(v) for v in pvals],
        "buffers": [np.asarray(v) for v in bvals],
        "gen_config": {"batch": batch, "prompt_len": prompt_len,
                       "max_len": max_len, **sample_kwargs},
    }
    if engine_slots is not None and engine_paged:
        from ..serving.engine import (build_paged_chunk_fn,
                                      build_slot_block_fn,
                                      init_slot_state)
        if max_len % engine_block_size != 0:
            raise ValueError(
                f"max_len={max_len} must be a multiple of "
                f"engine_block_size={engine_block_size}")
        max_blocks = max_len // engine_block_size
        if engine_num_blocks is None:
            engine_num_blocks = 1 + engine_slots * max_blocks
        if engine_prefill_chunk is None:
            engine_prefill_chunk = 2 * engine_block_size
        pool0 = model.init_paged_kv_cache(engine_num_blocks,
                                          engine_block_size)
        pflat, ptree = jax.tree.flatten(
            pool0, is_leaf=lambda x: isinstance(x, Tensor))
        eng_holder = {"tree": ptree}
        eng_pure = build_decode_step(model, None, eng_holder)
        pool_specs = tuple(jax.ShapeDtypeStruct(c._value.shape,
                                                c._value.dtype)
                           for c in pflat)
        state_specs = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            init_slot_state(engine_slots))
        state_specs["table"] = jax.ShapeDtypeStruct(
            (engine_slots, max_blocks), jnp.int32)
        block_fn = build_slot_block_fn(eng_pure, engine_decode_block,
                                       paged=True)
        exp_block = jax.export.export(jax.jit(block_fn))(
            pspecs, bspecs, pool_specs, state_specs)
        chunk_fn = build_paged_chunk_fn(eng_pure, engine_prefill_chunk)
        exp_chunk = jax.export.export(jax.jit(chunk_fn))(
            pspecs, bspecs,
            jax.ShapeDtypeStruct((1, engine_prefill_chunk), jnp.int32),
            pool_specs,
            jax.ShapeDtypeStruct((1, max_blocks), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((2,), jnp.uint32),
            jax.ShapeDtypeStruct((), jnp.float32),
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.float32))
        blob["engine"] = {
            "block": exp_block.serialize(),
            "chunk": exp_chunk.serialize(),
            "pool_specs": [(tuple(s.shape), str(np.dtype(s.dtype)))
                           for s in pool_specs],
            # arities recorded like the dense engine's block_outputs:
            # block emits (cache, state, toks, lives, oks), the chunk
            # program (tok0, cache) — a serving host can tell what the
            # artifact carries without deserializing anything
            "config": {"paged": True, "num_slots": engine_slots,
                       "max_len": max_len,
                       "decode_block": engine_decode_block,
                       "block_size": engine_block_size,
                       "num_blocks": engine_num_blocks,
                       "prefill_chunk": engine_prefill_chunk,
                       "kv_int8": False,
                       "block_outputs": 5, "chunk_outputs": 2},
        }
    elif engine_slots is not None:
        from ..serving.engine import (build_slot_block_fn,
                                      build_slot_prefill_fn,
                                      init_slot_state)
        pool0 = model.init_kv_cache(engine_slots, max_len)
        pflat, ptree = jax.tree.flatten(
            pool0, is_leaf=lambda x: isinstance(x, Tensor))
        eng_holder = {"tree": ptree}
        # per-slot sampling rides the state arrays — the exported block
        # serves every sampling config, so sample_kwargs=None here
        eng_pure = build_decode_step(model, None, eng_holder)
        pool_specs = tuple(jax.ShapeDtypeStruct(c._value.shape,
                                                c._value.dtype)
                           for c in pflat)
        row_specs = tuple(((1,) + s.shape[1:], s.dtype)
                          for s in pool_specs)
        state_specs = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            init_slot_state(engine_slots))
        block_fn = build_slot_block_fn(eng_pure, engine_decode_block)
        exp_block = jax.export.export(jax.jit(block_fn))(
            pspecs, bspecs, pool_specs, state_specs)
        prefills = {}
        for lb in sorted(set(int(b) for b in engine_prompt_buckets)):
            pre = build_slot_prefill_fn(eng_pure, row_specs)
            prefills[lb] = jax.export.export(jax.jit(pre))(
                pspecs, bspecs,
                jax.ShapeDtypeStruct((1, lb), jnp.int32),
                jax.ShapeDtypeStruct((1,), jnp.int32),
                jax.ShapeDtypeStruct((2,), jnp.uint32),
                jax.ShapeDtypeStruct((), jnp.float32),
                jax.ShapeDtypeStruct((), jnp.int32),
                jax.ShapeDtypeStruct((), jnp.float32)).serialize()
        blob["engine"] = {
            "block": exp_block.serialize(),
            "prefill": prefills,
            "pool_specs": [(tuple(s.shape), str(np.dtype(s.dtype)))
                           for s in pool_specs],
            # the decode block emits (cache, state, toks, lives, oks)
            # since the NaN-sentinel — record the arity so a serving
            # host can tell whether the artifact carries the flags
            # (pre-sentinel 4-output artifacts load fine: the engine
            # pads the missing flags with None)
            "config": {"num_slots": engine_slots, "max_len": max_len,
                       "decode_block": engine_decode_block,
                       "prompt_buckets": sorted(
                           int(b) for b in engine_prompt_buckets),
                       "block_outputs": 5},
        }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    out = path + ".pdgen"
    with open(out, "wb") as f:
        pickle.dump(blob, f, protocol=pickle.HIGHEST_PROTOCOL)
    return out


class GenerationPredictor:
    """Serving-side decode loop over the AOT artifact of
    :func:`export_decoder` — no model code or tracing needed."""

    def __init__(self, path: str):
        if not path.endswith(".pdgen"):
            path = path + ".pdgen"
        with open(path, "rb") as f:
            blob = pickle.load(f)
        self._prefill = jax.export.deserialize(blob["prefill"])
        self._step = jax.export.deserialize(blob["step"])
        self._params = [jnp.asarray(v) for v in blob["params"]]
        self._buffers = [jnp.asarray(v) for v in blob["buffers"]]
        self.gen_config = blob["gen_config"]
        self._engine_blob = blob if "engine" in blob else None
        self._server = None

    def generate(self, input_ids: np.ndarray, max_new_tokens: int = 20,
                 seed: int = 0) -> np.ndarray:
        cfg = self.gen_config
        ids = jnp.asarray(np.asarray(input_ids), jnp.int32)
        b, s = ids.shape
        if (b, s) != (cfg["batch"], cfg["prompt_len"]):
            raise ValueError(
                f"input shape {(b, s)} != exported "
                f"({cfg['batch']}, {cfg['prompt_len']})")
        if max_new_tokens <= 0:
            return np.asarray(ids)
        capacity = cfg["max_len"] - s
        if max_new_tokens > capacity:
            raise ValueError(
                f"max_new_tokens={max_new_tokens} exceeds the exported "
                f"cache capacity ({capacity} = max_len {cfg['max_len']} "
                f"- prompt {s}); re-export with a larger max_len")
        key = jax.random.PRNGKey(seed)
        key, sub = jax.random.split(key)
        tok, cache = self._prefill.call(self._params, self._buffers,
                                        ids, sub)
        toks = [tok]
        for i in range(1, max_new_tokens):
            key, sub = jax.random.split(key)
            pos = jnp.asarray(s + i - 1, jnp.int32)
            tok, cache = self._step.call(self._params, self._buffers,
                                         tok[:, None], tuple(cache),
                                         pos, sub)
            toks.append(tok)
        gen = jnp.stack(toks, axis=1)
        return np.asarray(jnp.concatenate([ids, gen], axis=1))

    def serve(self, requests, run: bool = True):
        """Continuous-batching serving from the artifact alone: builds
        the SAME ``serving.Server`` loop over the exported slot-pool
        engine programs (requires ``export_decoder(...,
        engine_slots=N)``). ``requests``: iterable of dicts with keys
        matching :meth:`serving.Server.submit` (``prompt`` required).
        Returns the Server (``run=False``) or its results dict."""
        if self._engine_blob is None:
            raise ValueError(
                "this artifact has no engine programs; re-export with "
                "export_decoder(..., engine_slots=N)")
        from ..serving import ContinuousBatchingEngine, Server
        from ..serving.engine import ArtifactStepBackend
        if self._server is None:
            cfgs = self._engine_blob["engine"]["config"]
            if cfgs.get("paged"):
                from ..serving.paging import PagedArtifactStepBackend
                backend = PagedArtifactStepBackend(self._engine_blob)
                # is_paged on the backend routes the factory to the
                # PagedEngine (chunked prefill + block manager)
                engine = ContinuousBatchingEngine(backend=backend)
            else:
                backend = ArtifactStepBackend(self._engine_blob)
                engine = ContinuousBatchingEngine(
                    backend=backend,
                    prompt_buckets=cfgs["prompt_buckets"])
            self._server = Server(engine)
        server = self._server
        for req in requests:
            server.submit(**dict(req))
        if not run:
            return server
        return server.run_until_idle()


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def convert_to_predictor(layer, input_spec, path) -> Predictor:
    """export_model + create_predictor in one step."""
    model_path = export_model(layer, input_spec, path)
    cfg = Config(model_path)
    return Predictor(cfg)
