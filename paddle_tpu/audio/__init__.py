"""Audio features (``paddle.audio`` parity: functional + features).

Reference parity: python/paddle/audio/ (features.layers Spectrogram /
MelSpectrogram / LogMelSpectrogram / MFCC, functional window/mel helpers
— verify). Built on paddle_tpu.signal.stft (XLA FFT HLO), so feature
extraction fuses into jitted pipelines.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from .. import signal as _signal
from ..nn import Layer
from ..tensor import Tensor, apply_op, to_tensor

__all__ = ["functional", "features", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC"]


class functional:
    @staticmethod
    def get_window(window: str, win_length: int, fftbins: bool = True):
        n = win_length
        if window in ("hann", "hanning"):
            w = np.hanning(n + 1)[:-1] if fftbins else np.hanning(n)
        elif window == "hamming":
            w = np.hamming(n + 1)[:-1] if fftbins else np.hamming(n)
        elif window == "blackman":
            w = np.blackman(n + 1)[:-1] if fftbins else np.blackman(n)
        elif window == "bartlett":
            w = np.bartlett(n + 1)[:-1] if fftbins else np.bartlett(n)
        elif window in ("ones", "boxcar", "rectangular"):
            w = np.ones(n)
        else:
            raise ValueError(f"unsupported window {window!r}")
        return to_tensor(w.astype(np.float32))

    @staticmethod
    def hz_to_mel(f, htk: bool = False):
        if htk:
            return 2595.0 * np.log10(1.0 + np.asarray(f) / 700.0)
        f = np.asarray(f, np.float64)
        f_min, f_sp = 0.0, 200.0 / 3
        mels = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        return np.where(f >= min_log_hz,
                        min_log_mel + np.log(f / min_log_hz) / logstep,
                        mels)

    @staticmethod
    def mel_to_hz(m, htk: bool = False):
        if htk:
            return 700.0 * (10.0 ** (np.asarray(m) / 2595.0) - 1.0)
        m = np.asarray(m, np.float64)
        f_min, f_sp = 0.0, 200.0 / 3
        freqs = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        return np.where(m >= min_log_mel,
                        min_log_hz * np.exp(logstep * (m - min_log_mel)),
                        freqs)

    @staticmethod
    def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                             f_min: float = 0.0,
                             f_max: float = None, htk: bool = False,
                             norm: str = "slaney"):
        """(n_mels, n_fft//2+1) triangular mel filterbank."""
        f_max = f_max or sr / 2
        fft_freqs = np.linspace(0, sr / 2, n_fft // 2 + 1)
        mel_pts = np.linspace(functional.hz_to_mel(f_min, htk),
                              functional.hz_to_mel(f_max, htk), n_mels + 2)
        hz_pts = functional.mel_to_hz(mel_pts, htk)
        fb = np.zeros((n_mels, len(fft_freqs)))
        for i in range(n_mels):
            lo, ctr, hi = hz_pts[i], hz_pts[i + 1], hz_pts[i + 2]
            up = (fft_freqs - lo) / max(ctr - lo, 1e-10)
            down = (hi - fft_freqs) / max(hi - ctr, 1e-10)
            fb[i] = np.maximum(0, np.minimum(up, down))
        if norm == "slaney":
            enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
            fb *= enorm[:, None]
        return to_tensor(fb.astype(np.float32))

    @staticmethod
    def power_to_db(x, ref_value: float = 1.0, amin: float = 1e-10,
                    top_db: float = 80.0):
        def f(v):
            db = 10.0 * jnp.log10(jnp.maximum(v, amin))
            db -= 10.0 * jnp.log10(jnp.maximum(ref_value, amin))
            if top_db is not None:
                db = jnp.maximum(db, jnp.max(db) - top_db)
            return db
        return apply_op(f, x)

    @staticmethod
    def create_dct(n_mfcc: int, n_mels: int, norm: str = "ortho"):
        n = np.arange(n_mels)
        k = np.arange(n_mfcc)[:, None]
        dct = np.cos(math.pi / n_mels * (n + 0.5) * k)
        if norm == "ortho":
            dct[0] *= 1.0 / math.sqrt(2)
            dct *= math.sqrt(2.0 / n_mels)
        return to_tensor(dct.astype(np.float32).T)   # (n_mels, n_mfcc)


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512, hop_length: int = None,
                 win_length: int = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.register_buffer(
            "window", functional.get_window(window, self.win_length))

    def forward(self, x):
        spec = _signal.stft(x, self.n_fft, self.hop_length,
                            self.win_length, window=self.window,
                            center=self.center, pad_mode=self.pad_mode)
        return apply_op(lambda s: jnp.abs(s) ** self.power, spec)


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: int = None, win_length: int = None,
                 window: str = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0,
                 f_max: float = None, htk: bool = False,
                 norm: str = "slaney"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power, center, pad_mode)
        self.register_buffer("fbank", functional.compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm))

    def forward(self, x):
        spec = self.spectrogram(x)          # (..., freq, frames)
        return apply_op(lambda f, s: jnp.einsum("mf,...ft->...mt", f, s),
                        self.fbank, spec)


class LogMelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512, **kw):
        super().__init__()
        self.top_db = kw.pop("top_db", 80.0)
        self.ref_value = kw.pop("ref_value", 1.0)
        self.amin = kw.pop("amin", 1e-10)
        self.mel_spectrogram = MelSpectrogram(sr, n_fft, **kw)

    def forward(self, x):
        return functional.power_to_db(self.mel_spectrogram(x),
                                      self.ref_value, self.amin,
                                      self.top_db)


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 512,
                 n_mels: int = 64, **kw):
        super().__init__()
        self.log_mel = LogMelSpectrogram(sr, n_fft, n_mels=n_mels, **kw)
        self.register_buffer("dct",
                             functional.create_dct(n_mfcc, n_mels))

    def forward(self, x):
        logmel = self.log_mel(x)            # (..., n_mels, frames)
        return apply_op(lambda d, s: jnp.einsum("mk,...mt->...kt", d, s),
                        self.dct, logmel)


class features:
    Spectrogram = Spectrogram
    MelSpectrogram = MelSpectrogram
    LogMelSpectrogram = LogMelSpectrogram
    MFCC = MFCC
