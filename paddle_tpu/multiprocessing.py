"""paddle.multiprocessing — reference parity shim
(python/paddle/incubate/multiprocessing — verify). The reference adds
CUDA-tensor-sharing reductions to std multiprocessing; jax arrays are
immutable device buffers with no cross-process share path, so this
module re-exports std multiprocessing plus the launch-contract spawn
helper (each child is its own jax runtime)."""
from multiprocessing import *          # noqa: F401,F403
from multiprocessing import get_context, get_start_method  # noqa: F401

from .distributed.launch_utils import spawn  # noqa: F401
