"""Signal processing (``paddle.signal`` parity: stft/istft).

Reference parity: python/paddle/signal.py — verify. TPU-native: framing
is a gather (XLA dynamic-slice batch), the transform itself is the XLA
FFT HLO via paddle_tpu.fft.
"""
from __future__ import annotations

import jax.numpy as jnp

from .tensor import Tensor, apply_op

__all__ = ["frame", "overlap_add", "stft", "istft"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice overlapping frames: (..., n) -> (..., frame_length, n_frames)
    for axis=-1 (paddle layout)."""
    def f(v):
        n = v.shape[-1]
        n_frames = 1 + (n - frame_length) // hop_length
        starts = jnp.arange(n_frames) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]
        frames = v[..., idx]                     # (..., n_frames, flen)
        return jnp.swapaxes(frames, -1, -2)      # (..., flen, n_frames)
    if axis == 0:
        xt = apply_op(lambda v: jnp.moveaxis(v, 0, -1), x)
        out = apply_op(f, xt)
        return apply_op(lambda v: jnp.moveaxis(v, (-2, -1), (0, 1)), out)
    return apply_op(f, x)


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame: (..., frame_length, n_frames) -> (..., n)."""
    def f(v):
        flen, n_frames = v.shape[-2], v.shape[-1]
        n = flen + hop_length * (n_frames - 1)
        out = jnp.zeros(v.shape[:-2] + (n,), v.dtype)
        for i in range(n_frames):
            out = out.at[..., i * hop_length:i * hop_length + flen].add(
                v[..., :, i])
        return out
    if axis == 0:
        xt = apply_op(lambda v: jnp.moveaxis(v, (0, 1), (-2, -1)), x)
        out = apply_op(f, xt)
        return apply_op(lambda v: jnp.moveaxis(v, -1, 0), out)
    return apply_op(f, x)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """(batch?, n) -> (batch?, n_fft//2+1 | n_fft, n_frames) complex."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def f(v, *w):
        win = w[0] if w else jnp.ones(win_length, v.dtype)
        if win_length < n_fft:
            lp = (n_fft - win_length) // 2
            win = jnp.pad(win, (lp, n_fft - win_length - lp))
        if center:
            pad = [(0, 0)] * (v.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            v = jnp.pad(v, pad, mode=pad_mode)
        n = v.shape[-1]
        n_frames = 1 + (n - n_fft) // hop_length
        starts = jnp.arange(n_frames) * hop_length
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        frames = v[..., idx] * win                    # (..., n_frames, n_fft)
        spec = jnp.fft.rfft(frames, axis=-1) if onesided \
            else jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.swapaxes(spec, -1, -2)             # (..., freq, frames)

    args = (x, window) if window is not None else (x,)
    return apply_op(f, *args)


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def f(v, *w):
        win = w[0] if w else jnp.ones(win_length, jnp.float32)
        if win_length < n_fft:
            lp = (n_fft - win_length) // 2
            win = jnp.pad(win, (lp, n_fft - win_length - lp))
        spec = jnp.swapaxes(v, -1, -2)                # (..., frames, freq)
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        frames = jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided \
            else jnp.fft.ifft(spec, axis=-1).real
        if return_complex:
            frames = jnp.fft.ifft(spec, axis=-1)
        frames = frames * win
        n_frames = frames.shape[-2]
        n = n_fft + hop_length * (n_frames - 1)
        out = jnp.zeros(frames.shape[:-2] + (n,), frames.dtype)
        wsum = jnp.zeros((n,), jnp.float32)
        for i in range(n_frames):
            sl = slice(i * hop_length, i * hop_length + n_fft)
            out = out.at[..., sl].add(frames[..., i, :])
            wsum = wsum.at[sl].add(win.astype(jnp.float32) ** 2)
        out = out / jnp.where(wsum > 1e-11, wsum, 1.0)
        if center:
            out = out[..., n_fft // 2:]
            if length is None:
                out = out[..., :out.shape[-1] - n_fft // 2]
        if length is not None:
            out = out[..., :length]
        return out

    args = (x, window) if window is not None else (x,)
    return apply_op(f, *args)
