"""paddle.save / paddle.load parity (reference:
python/paddle/framework/io.py — verify). Tensors are stored as numpy inside
a pickle; nested dicts/lists (state_dicts, opt states) round-trip."""
from __future__ import annotations

import os
import pickle

import jax.numpy as jnp
import numpy as np

from .tensor import Tensor, Parameter

__all__ = ["save", "load"]

_MAGIC = b"PDTPU1\x00"


def _pack(obj):
    if isinstance(obj, Parameter):
        return {"__pdtpu__": "param", "v": np.asarray(obj._value),
                "trainable": obj.trainable}
    if isinstance(obj, Tensor):
        return {"__pdtpu__": "tensor", "v": np.asarray(obj._value),
                "stop_gradient": obj.stop_gradient}
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, dict):
        tag = obj.get("__pdtpu__")
        if tag == "param":
            if return_numpy:
                return obj["v"]
            p = Parameter(jnp.asarray(obj["v"]),
                          trainable=obj.get("trainable", True))
            return p
        if tag == "tensor":
            if return_numpy:
                return obj["v"]
            return Tensor(jnp.asarray(obj["v"]),
                          stop_gradient=obj.get("stop_gradient", True))
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        f.write(_MAGIC)
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        head = f.read(len(_MAGIC))
        if head != _MAGIC:
            f.seek(0)
        obj = pickle.load(f)
    return _unpack(obj, return_numpy)
