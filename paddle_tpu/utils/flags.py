"""FLAGS registry (reference: paddle/common/flags.cc PD_DEFINE_EXPORTED_*,
paddle.set_flags/get_flags — verify). Env override: FLAGS_<name>."""
from __future__ import annotations

import os
from typing import Any

__all__ = ["define_flag", "get_flags", "set_flags", "FLAGS", "env_flag",
           "env_bool", "env_int", "env_float", "env_set", "env_str"]


def env_bool(name: str, default: bool = False) -> bool:
    """Read a boolean env toggle with uniform falsy spellings
    ('', '0', 'false', 'off', 'no' — case/whitespace-insensitive).
    Shared by PT_FUSION_PASSES, the collectives flags and the serving
    flags so toggle semantics never drift between subsystems."""
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() not in ("", "0", "false", "off", "no")


# historical name — env_bool is the canonical spelling
env_flag = env_bool


def env_int(name: str, default: int) -> int:
    """Read a PT_* integer env knob. Empty/whitespace values fall back
    to the default instead of raising mid-import (a stray `export
    PT_X=` in a session script must not take the whole package down);
    a malformed non-empty value still raises loudly."""
    v = os.environ.get(name)
    if v is None or not v.strip():
        return default
    return int(v.strip())


def env_float(name: str, default: float) -> float:
    """Read a float env knob (same lenient-empty / strict-malformed
    contract as :func:`env_int`)."""
    v = os.environ.get(name)
    if v is None or not v.strip():
        return default
    return float(v.strip())


def env_str(name: str, default: str = "") -> str:
    """Read a PT_* string env knob (stripped)."""
    v = os.environ.get(name)
    return default if v is None else v.strip()


def env_set(name: str) -> bool:
    """Whether an env knob is PRESENT at all (even set-empty or "0") —
    for resolution orders where "explicitly set" must beat other
    sources regardless of the value (e.g. the flash-block preference:
    env beats the autotune table, and `NAME=0` means "kernel defaults",
    not "unset")."""
    return os.environ.get(name) is not None

_REGISTRY: dict[str, Any] = {}


def _env_cast(raw: str, default):
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


def define_flag(name: str, default, help_: str = ""):
    env = os.environ.get(f"FLAGS_{name}")
    _REGISTRY[name] = _env_cast(env, default) if env is not None else default


def get_flags(flags=None):
    if flags is None:
        return dict(_REGISTRY)
    if isinstance(flags, str):
        flags = [flags]
    return {f: _REGISTRY[f.replace("FLAGS_", "")] for f in flags}


def set_flags(flags: dict):
    for k, v in flags.items():
        _REGISTRY[k.replace("FLAGS_", "")] = v


class _Flags:
    def __getattr__(self, name):
        try:
            return _REGISTRY[name]
        except KeyError:
            raise AttributeError(name)


FLAGS = _Flags()

# core flags (subset of the reference's ~200 FLAGS_* — verify)
define_flag("allocator_strategy", "auto_growth")
define_flag("cudnn_deterministic", False)
define_flag("embedding_deterministic", 0)
define_flag("check_nan_inf", False)
define_flag("benchmark", False)
define_flag("use_flash_attention", True)
define_flag("log_level", 0)
define_flag("tpu_matmul_precision", "default")
