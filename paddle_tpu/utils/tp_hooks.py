"""Trace-time tensor-parallel hooks for the serving decode block.

The model's forward asks "am I sharded, and where do the gathers/
reduces go" through these functions. They are no-ops (one module-global
check at TRACE time, zero runtime cost) outside a sharded serving
trace, so the same model code serves 1-chip and TP.

This module lives in ``utils`` — NOT in ``serving`` — on purpose:
``models/llama.py`` calls the hooks from its forward, and importing
them from the serving package would pull the whole serving stack
(engine/paging/server/resilience/observability) into every
training-only model import AND invert the layering that
``serving/engine.py`` keeps one-directional by importing models lazily.
Everything heavy (collectives, tensor wrappers) is imported lazily
inside the active path; the module itself depends only on the stdlib
and jax.numpy. ``serving/tp.py`` owns arming: it pushes a
:class:`TPSpec` around every sharded trace via :func:`active`.
"""
from __future__ import annotations

import contextlib
import dataclasses
from typing import List, Optional

import jax.numpy as jnp

__all__ = ["TPSpec", "current_tp", "active", "maybe_gather",
           "maybe_gather_logits", "maybe_reduce"]


@dataclasses.dataclass(frozen=True)
class TPSpec:
    """What a sharded serving trace needs to know: the hierarchical
    collective plan over the TP mesh axes, the total shard degree, the
    weight layout (``"exact"`` | ``"psum"``), and whether the psum-mode
    hidden-state all-reduce rides the int8 wire format."""
    plan: object          # distributed.collectives.HierarchyPlan
    degree: int
    mode: str
    int8: bool


_ACTIVE: List[TPSpec] = []
_BOUND_SINK: Optional[list] = None   # armed by tp.py's int8 bound probe


def current_tp() -> Optional[TPSpec]:
    return _ACTIVE[-1] if _ACTIVE else None


@contextlib.contextmanager
def active(spec: TPSpec):
    _ACTIVE.append(spec)
    try:
        yield
    finally:
        _ACTIVE.pop()


def _gather_last_dim(x, plan):
    """All-gather shards of the LAST dim (chunks in linear-index order
    over the plan axes — matching the P(..., axes) weight layout).
    Pure data movement: bit-exact."""
    from ..distributed.collectives.hierarchical import hier_all_gather
    x = jnp.moveaxis(x, -1, 0)
    x = hier_all_gather(x, plan)
    return jnp.moveaxis(x, 0, -1)


def maybe_gather(t, full_width: int):
    """Exact-mode gather in front of a replicated row-parallel weight
    (attention heads before o_proj, MLP activation before down_proj).
    No-op when TP is off, the tensor is already full width (layer not
    sharded), or mode is psum (row-parallel follows instead)."""
    spec = current_tp()
    if spec is None or spec.mode != "exact" or \
            t.shape[-1] == full_width:
        return t
    from ..tensor import apply_op
    return apply_op(lambda v: _gather_last_dim(v, spec.plan), t)


def maybe_gather_logits(t, vocab_size: int):
    """The final-logits all-gather (both modes): vocab-sharded lm_head
    shards -> full logits through the hierarchical collectives path."""
    spec = current_tp()
    if spec is None or t.shape[-1] == vocab_size:
        return t
    from ..tensor import apply_op
    return apply_op(lambda v: _gather_last_dim(v, spec.plan), t)


def maybe_reduce(t):
    """Psum-mode hidden-state all-reduce behind a row-sharded weight
    (o_proj / down_proj partial sums). With ``int8`` the payload rides
    the EQuARX wire format; the bound probe (``_BOUND_SINK`` armed by
    serving/tp.py) additionally collects the runtime error bound of
    every hop."""
    spec = current_tp()
    if spec is None or spec.mode != "psum":
        return t
    from ..distributed.collectives.hierarchical import hier_all_reduce
    from ..distributed.collectives.quantized import quantized_all_reduce
    from ..tensor import apply_op

    def red(v):
        if not spec.int8:
            return hier_all_reduce(v, spec.plan)
        if _BOUND_SINK is not None:
            out, bound = quantized_all_reduce(v, spec.plan,
                                              return_error_bound=True)
            _BOUND_SINK.append(bound)
            return out
        return quantized_all_reduce(v, spec.plan)

    return apply_op(red, t)
