"""Systematic error layer (reference: paddle/common/enforce.h
PADDLE_ENFORCE_* macros + the typed error hierarchy surfaced to Python
as paddle.base.core.EnforceNotMet subtypes — verify).

TPU-native design: the reference's macro layer exists because C++ has
no exceptions-with-context discipline; here the value is (a) ONE typed
error hierarchy users can catch precisely, (b) enforce helpers that
produce uniform, actionable messages (expected vs actual, a hint), and
(c) shape/dtype checks that read well at call sites. XLA/jax errors are
re-raised through `rethrow` with framework context attached."""
from __future__ import annotations

from typing import Any, Optional, Sequence

__all__ = ["EnforceNotMet", "InvalidArgumentError", "NotFoundError",
           "OutOfRangeError", "AlreadyExistsError", "PermissionDeniedError",
           "PreconditionNotMetError", "UnimplementedError",
           "UnavailableError", "ExecutionTimeoutError", "enforce",
           "enforce_eq", "enforce_gt", "enforce_ge", "enforce_in",
           "enforce_shape", "enforce_dtype", "rethrow"]


class EnforceNotMet(RuntimeError):
    """Root of the framework error hierarchy (reference: EnforceNotMet)."""

    def __init__(self, message: str, hint: Optional[str] = None):
        self.hint = hint
        full = message if hint is None else f"{message}\n  [Hint: {hint}]"
        self._formatted = full
        super().__init__(full)

    def __str__(self):
        # KeyError.__str__ would repr-quote the message and escape the
        # hint newline; always render the formatted text
        return self._formatted


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError, ValueError):
    # also a ValueError: pre-enforce call sites raised ValueError for
    # range violations, and callers catching it must keep working
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class PermissionDeniedError(EnforceNotMet):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    pass


class ExecutionTimeoutError(EnforceNotMet, TimeoutError):
    pass


def enforce(cond: Any, message: str, hint: Optional[str] = None,
            error: type = PreconditionNotMetError):
    """PADDLE_ENFORCE: raise ``error`` with a uniform message when the
    condition is falsy."""
    if not cond:
        raise error(message, hint)
    return cond


def enforce_eq(actual, expected, what: str, hint: Optional[str] = None,
               error: type = InvalidArgumentError):
    if actual != expected:
        raise error(f"{what}: expected {expected!r}, got {actual!r}", hint)
    return actual


def enforce_gt(actual, bound, what: str, hint: Optional[str] = None):
    if not actual > bound:
        raise InvalidArgumentError(
            f"{what}: expected > {bound!r}, got {actual!r}", hint)
    return actual


def enforce_ge(actual, bound, what: str, hint: Optional[str] = None):
    if not actual >= bound:
        raise InvalidArgumentError(
            f"{what}: expected >= {bound!r}, got {actual!r}", hint)
    return actual


def enforce_in(value, allowed: Sequence, what: str,
               hint: Optional[str] = None):
    if value not in allowed:
        raise InvalidArgumentError(
            f"{what}: expected one of {list(allowed)!r}, got {value!r}",
            hint)
    return value


def enforce_shape(x, expected_shape: Sequence, what: str = "tensor",
                  hint: Optional[str] = None):
    """Shape check with wildcards: None/-1 entries match any size."""
    shape = tuple(getattr(x, "shape", x))
    exp = tuple(expected_shape)
    ok = len(shape) == len(exp) and all(
        e is None or e == -1 or int(e) == int(s)
        for s, e in zip(shape, exp))
    if not ok:
        raise InvalidArgumentError(
            f"{what}: expected shape {list(exp)!r}, got {list(shape)!r}",
            hint)
    return x


def enforce_dtype(x, expected, what: str = "tensor",
                  hint: Optional[str] = None):
    import numpy as np
    if isinstance(expected, type):      # float/int/bool follow the
        from ..framework import convert_dtype   # framework policy
        exp = np.dtype(convert_dtype(expected))
    else:
        try:
            exp = np.dtype(expected)   # validate as-is: no 64->32
            #                            policy for explicit strings
        except TypeError:
            from ..framework import convert_dtype
            exp = np.dtype(convert_dtype(expected))
    actual = np.dtype(getattr(x, "dtype", x))
    if actual != exp:
        raise InvalidArgumentError(
            f"{what}: expected dtype {exp}, got {actual}", hint)
    return x


def rethrow(exc: BaseException, context: str,
            error: type = EnforceNotMet):
    """Wrap a lower-level (jax/XLA) exception with framework context —
    the reference's error-stack annotation (external error classes
    decoded into EnforceNotMet — verify)."""
    raise error(f"{context}: {type(exc).__name__}: {exc}") from exc
