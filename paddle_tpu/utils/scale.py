"""Abstract (weight-free) model construction for AOT scale checks.

Reference parity: the reference's auto-parallel cost model / memory
estimator (python/paddle/distributed/auto_parallel/static/cost/ —
verify) answers "does this config fit the cluster?" without running it.

TPU-native design: XLA's own compiler IS the cost model. Build the model
with every Parameter backed by a ``jax.ShapeDtypeStruct`` (no host
memory), attach NamedShardings for the target mesh, AOT-lower + compile
the full fused train step over a virtual device mesh, and read
``memory_analysis()`` / ``cost_analysis()`` — the compiler's per-device
peak-memory estimate for hardware we don't have attached. Used by
``scale_check.py`` to validate Llama-13B TP×PP on a virtual v5p-32."""
from __future__ import annotations

import contextlib

import jax
import numpy as np

__all__ = ["abstract_init", "attach_shardings", "abstract_state_specs"]


@contextlib.contextmanager
def abstract_init(dtype=None):
    """Inside this context, ``Layer.create_parameter`` yields Parameters
    whose ``_value`` is a ShapeDtypeStruct — model construction at any
    size without materializing weights. ``dtype`` overrides the param
    dtype (e.g. "bfloat16" for a bf16-weights scale check)."""
    from ..nn.layer import Layer
    from ..tensor import Parameter
    from ..framework import convert_dtype

    orig = Layer.create_parameter
    forced = convert_dtype(dtype) if dtype else None

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from .. import framework
        dt = forced or convert_dtype(dtype) or self._dtype or \
            framework.state().default_dtype
        p = Parameter(jax.ShapeDtypeStruct(
            tuple(int(s) for s in shape), np.dtype(dt)))
        if attr is not None:
            if getattr(attr, "learning_rate", None) is not None:
                p.optimize_attr["learning_rate"] = attr.learning_rate
            if getattr(attr, "trainable", True) is False:
                p.stop_gradient = True
                p.trainable = False
        return p

    Layer.create_parameter = create_parameter
    try:
        yield
    finally:
        Layer.create_parameter = orig


def attach_shardings(model, mesh):
    """Abstract analogue of sharding_utils.place_model: rewrap every
    param spec with its NamedSharding for ``mesh`` (replicated when the
    spec is absent or not divisible). Buffers stay concrete (they are
    small) — callers should pass them through device_put as usual."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..distributed.sharding_utils import filter_spec, _divisible

    for _, p in model.named_parameters():
        v = p._value
        if not isinstance(v, jax.ShapeDtypeStruct):
            continue
        spec = filter_spec(getattr(p, "_sharding_spec", None), mesh,
                           len(v.shape))
        if not _divisible(v.shape, spec, mesh):
            spec = P()
        p._value = jax.ShapeDtypeStruct(
            v.shape, v.dtype, sharding=NamedSharding(mesh, spec))
    return model


def abstract_state_specs(opt_state, params):
    """Give optimizer-slot specs the sharding of their parameter (the
    shard_optimizer default) so AOT lowering sees the real placement."""
    slots = opt_state["slots"]
    out = {}
    for pname, s in slots.items():
        pspec = params.get(pname)
        psharding = getattr(pspec, "sharding", None) \
            if pspec is not None else None
        out[pname] = {
            k: jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=psharding)
            if isinstance(v, jax.ShapeDtypeStruct)
            and psharding is not None and v.shape == pspec.shape else v
            for k, v in s.items()}
    return {"slots": out, "step": opt_state["step"]}
