"""paddle.utils.download — weight/file fetch with a local cache
(reference parity: python/paddle/utils/download.py get_weights_path_
from_url / get_path_from_url — verify).

TPU-pod reality baked in: training hosts frequently have ZERO egress
(this build environment does). The cache directory is therefore the
first-class path — anything already present under ``PT_HOME`` (default
``~/.cache/paddle_tpu``) is used without touching the network, and a
download attempt with no egress raises one clear error naming the
expected cache location instead of a DNS timeout stack."""
from __future__ import annotations

import hashlib
import os
import shutil

from .flags import env_float, env_str

__all__ = ["get_weights_path_from_url", "get_path_from_url"]

PT_HOME = env_str("PT_HOME") or os.path.join(
    os.path.expanduser("~"), ".cache", "paddle_tpu")
WEIGHTS_HOME = os.path.join(PT_HOME, "weights")


def _md5check(path: str, md5sum: str | None) -> bool:
    if not md5sum:
        return True
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest() == md5sum


def get_path_from_url(url: str, root_dir: str | None = None,
                      md5sum: str | None = None,
                      check_exist: bool = True) -> str:
    """Return a local path for ``url``: the cached copy if present
    (verified against ``md5sum`` when given), else download into the
    cache. ``file://`` URLs and plain local paths are linked into the
    cache without any network."""
    root_dir = root_dir or WEIGHTS_HOME
    os.makedirs(root_dir, exist_ok=True)
    if url.startswith("file://"):
        url = url[len("file://"):]
    if os.path.exists(url):                   # local file "url"
        dst = os.path.join(root_dir, os.path.basename(url))
        if os.path.abspath(dst) != os.path.abspath(url):
            shutil.copyfile(url, dst)
        return dst
    fname = os.path.basename(url.split("?")[0]) or "download"
    fullpath = os.path.join(root_dir, fname)
    if check_exist and os.path.exists(fullpath) and \
            _md5check(fullpath, md5sum):
        return fullpath
    try:
        import urllib.request
        tmp = fullpath + ".part"
        timeout = env_float("PT_DOWNLOAD_TIMEOUT", 30.0)
        # explicit timeout: a firewalled/blackholed egress (dropped
        # SYNs, the TPU-pod norm) must raise the clear error below, not
        # hang forever the way a timeout-less urlretrieve would
        with urllib.request.urlopen(url, timeout=timeout) as r, \
                open(tmp, "wb") as f:
            shutil.copyfileobj(r, f)
        if not _md5check(tmp, md5sum):
            os.remove(tmp)
            raise RuntimeError(f"md5 mismatch downloading {url}")
        os.replace(tmp, fullpath)
        return fullpath
    except Exception as e:
        raise RuntimeError(
            f"could not fetch {url!r} ({type(e).__name__}: {e}). This "
            f"host may have no egress (typical for TPU pods): place the "
            f"file at {fullpath!r} (or set PT_HOME) and re-run — cached "
            f"files are used without any network access.") from e


def get_weights_path_from_url(url: str,
                              md5sum: str | None = None) -> str:
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
