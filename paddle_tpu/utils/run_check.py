"""paddle.utils.run_check parity (reference:
python/paddle/utils/install_check.py — verify): smoke-test the install —
one matmul+grad on the default device, then a sharded matmul on all local
devices via a 1-D mesh."""
from __future__ import annotations


def run_check():
    import jax
    import jax.numpy as jnp
    import numpy as np

    devs = jax.devices()
    plat = devs[0].platform
    print(f"Running verify on 1 {plat} device.")
    import paddle_tpu as paddle
    x = paddle.to_tensor(np.random.rand(16, 16).astype("float32"),
                         stop_gradient=False)
    w = paddle.to_tensor(np.random.rand(16, 16).astype("float32"),
                         stop_gradient=False)
    y = paddle.matmul(x, w).sum()
    y.backward()
    assert x.grad is not None
    print(f"paddle_tpu works on 1 {plat} device.")

    if len(devs) > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        mesh = Mesh(np.array(devs), ("dp",))
        a = jax.device_put(jnp.ones((len(devs) * 8, 16)),
                           NamedSharding(mesh, P("dp", None)))
        b = jnp.ones((16, 16))
        out = jax.jit(lambda a, b: a @ b)(a, b)
        out.block_until_ready()
        print(f"paddle_tpu works on {len(devs)} {plat} devices.")
    print("paddle_tpu is installed successfully!")
