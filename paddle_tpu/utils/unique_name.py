"""Unique-name generator (reference: python/paddle/utils/unique_name.py
— the name scopes behind parameter/op auto-naming — verify)."""
from __future__ import annotations

import contextlib

__all__ = ["generate", "guard", "switch"]

_counters: dict[str, int] = {}


def generate(key: str = "tmp") -> str:
    _counters[key] = _counters.get(key, 0)
    name = f"{key}_{_counters[key]}"
    _counters[key] += 1
    return name


def switch(new_state=None):
    global _counters
    old = _counters
    _counters = {} if new_state is None else new_state
    return old


@contextlib.contextmanager
def guard(new_state=None):
    old = switch({} if new_state is None else new_state)
    try:
        yield
    finally:
        switch(old)
