"""Custom C++ op extension (``paddle.utils.cpp_extension`` parity).

Reference parity: python/paddle/utils/cpp_extension/ (CppExtension /
CUDAExtension / load: compile user C++ into a loadable op — verify;
C++ side PD_BUILD_OP in paddle/phi/api/ext).

TPU-native design: device code belongs in Pallas (see
paddle_tpu.ops.pallas); this module covers the HOST custom-op path —
user C++ compiled with g++ and invoked through ``jax.pure_callback`` so
it composes with jit/vmap (the XLA program calls back to host, runs the
C++ kernel on numpy buffers, and resumes). A custom VJP can be supplied
as a second C++ function, so custom ops stay differentiable.

Supported C ABI (documented contract, float32):
    extern "C" void NAME(const float* in, float* out, int64_t n);
elementwise/maplike over a contiguous buffer, out has in's shape — or
with an explicit output shape via ``out_like``.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor, apply_op

__all__ = ["load", "CppExtension", "get_build_directory"]

_BUILD_DIR = os.path.join(tempfile.gettempdir(), "paddle_tpu_extensions")


def get_build_directory():
    os.makedirs(_BUILD_DIR, exist_ok=True)
    return _BUILD_DIR


def _compile(name: str, sources: Sequence[str],
             extra_cxx_cflags=()) -> str:
    out = os.path.join(get_build_directory(), f"lib{name}.so")
    newest_src = max(os.path.getmtime(s) for s in sources)
    if not os.path.exists(out) or os.path.getmtime(out) < newest_src:
        cmd = ["g++", "-std=c++17", "-O2", "-shared", "-fPIC",
               *extra_cxx_cflags, *sources, "-o", out]
        r = subprocess.run(cmd, capture_output=True, text=True)
        if r.returncode != 0:
            raise RuntimeError(f"custom op build failed:\n{r.stderr}")
    return out


class _CustomOp:
    """One C function wrapped as a differentiable paddle op."""

    def __init__(self, lib, name: str,
                 backward: Optional[str] = None):
        self._fn = getattr(lib, name)
        self._fn.argtypes = [ctypes.POINTER(ctypes.c_float),
                             ctypes.POINTER(ctypes.c_float),
                             ctypes.c_int64]
        self._bwd = getattr(lib, backward) if backward else None
        if self._bwd is not None:
            self._bwd.argtypes = self._fn.argtypes
        self.__name__ = name

        def host_call(arr):
            arr = np.ascontiguousarray(arr, np.float32)
            out = np.empty_like(arr)
            self._fn(arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                     out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                     arr.size)
            return out

        def host_call_bwd(arr):
            arr = np.ascontiguousarray(arr, np.float32)
            out = np.empty_like(arr)
            self._bwd(arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                      out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                      arr.size)
            return out

        @jax.custom_vjp
        def op(x):
            return jax.pure_callback(
                host_call, jax.ShapeDtypeStruct(x.shape, jnp.float32), x,
                vmap_method="sequential")

        def fwd(x):
            return op(x), x

        def bwd(x, ct):
            if self._bwd is None:
                raise NotImplementedError(
                    f"custom op {name!r} has no backward function "
                    "(pass backward= to load)")
            grad_in = jax.pure_callback(
                host_call_bwd,
                jax.ShapeDtypeStruct(x.shape, jnp.float32), x,
                vmap_method="sequential")
            return (ct * grad_in,)

        op.defvjp(fwd, bwd)
        self._op = op

    def __call__(self, x):
        if isinstance(x, Tensor):
            return apply_op(self._op, x)
        return self._op(jnp.asarray(x))


class _ExtensionModule:
    def __init__(self, lib_path: str, ops: dict):
        self._lib = ctypes.CDLL(lib_path)
        for fname, bname in ops.items():
            setattr(self, fname, _CustomOp(self._lib, fname, bname))


def load(name: str, sources: Sequence[str], functions=None,
         extra_cxx_cflags=(), backward_map=None, verbose=False,
         **kwargs) -> _ExtensionModule:
    """Compile ``sources`` and expose ``functions`` as differentiable
    ops. ``backward_map`` maps forward name -> C function computing
    d(out)/d(in) pointwise (chain rule applied automatically)."""
    if functions is None:
        raise ValueError("pass functions=[...] naming the extern \"C\" "
                         "symbols to expose")
    lib_path = _compile(name, sources, extra_cxx_cflags)
    backward_map = backward_map or {}
    return _ExtensionModule(
        lib_path, {f: backward_map.get(f) for f in functions})


class CppExtension:
    """setup()-style parity shim: holds sources until load()."""

    def __init__(self, sources, **kwargs):
        self.sources = sources
        self.kwargs = kwargs
