"""Utilities (reference: python/paddle/utils/ — verify)."""
from . import flags        # noqa: F401
from . import enforce      # noqa: F401
from .run_check import run_check  # noqa: F401
from .enforce import (EnforceNotMet, InvalidArgumentError,  # noqa: F401
                      NotFoundError, OutOfRangeError,
                      AlreadyExistsError, PermissionDeniedError,
                      PreconditionNotMetError, UnimplementedError,
                      UnavailableError, ExecutionTimeoutError)


def try_import(module_name):
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        return None

from . import dlpack  # noqa: F401
from . import cpp_extension  # noqa: F401,E402
from . import unique_name    # noqa: F401,E402
from . import download       # noqa: F401,E402
from .download import get_weights_path_from_url  # noqa: F401,E402


def deprecated(update_to="", since="", reason="", level=0):
    """Decorator marking an API deprecated (reference:
    paddle.utils.deprecated, python/paddle/utils/deprecated.py — verify).
    Warns once per call site; level>=2 raises instead."""
    import functools
    import warnings

    def wrap(fn):
        msg = f"API '{getattr(fn, '__name__', fn)}' is deprecated"
        if since:
            msg += f" since {since}"
        if reason:
            msg += f": {reason}"
        if update_to:
            msg += f"; use '{update_to}' instead"

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            if level >= 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        inner.__deprecated_message__ = msg
        return inner
    return wrap
