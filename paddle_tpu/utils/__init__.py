"""Utilities (reference: python/paddle/utils/ — verify)."""
from . import flags        # noqa: F401
from . import enforce      # noqa: F401
from .run_check import run_check  # noqa: F401
from .enforce import (EnforceNotMet, InvalidArgumentError,  # noqa: F401
                      NotFoundError, OutOfRangeError,
                      AlreadyExistsError, PermissionDeniedError,
                      PreconditionNotMetError, UnimplementedError,
                      UnavailableError, ExecutionTimeoutError)


def try_import(module_name):
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        return None

from . import dlpack  # noqa: F401
from . import cpp_extension  # noqa: F401,E402
from . import unique_name    # noqa: F401,E402
