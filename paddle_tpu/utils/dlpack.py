"""paddle.utils.dlpack parity: zero-copy tensor exchange via the DLPack
protocol (reference: python/paddle/utils/dlpack.py — verify). jax arrays
speak the modern __dlpack__ protocol; ``to_dlpack`` returns a small
carrier exposing it (consumable by torch/numpy/jax ``from_dlpack``),
which also makes the paddle round-trip from_dlpack(to_dlpack(t)) work —
raw legacy capsules cannot be re-imported by jax 0.9."""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


class _DLPackCarrier:
    """Protocol object delegating to the underlying jax array."""

    def __init__(self, arr):
        self._arr = arr

    def __dlpack__(self, **kwargs):
        return self._arr.__dlpack__(**kwargs)

    def __dlpack_device__(self):
        return self._arr.__dlpack_device__()


def to_dlpack(x):
    """Tensor → DLPack protocol object (torch/numpy/jax can consume)."""
    val = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return _DLPackCarrier(val)


def from_dlpack(obj):
    """Any __dlpack__-capable object (incl. to_dlpack output) → Tensor."""
    if not hasattr(obj, "__dlpack__"):
        raise TypeError(
            "from_dlpack needs an object implementing __dlpack__ / "
            "__dlpack_device__ (a legacy raw PyCapsule cannot be "
            f"re-imported by jax); got {type(obj).__name__}")
    return Tensor(jnp.from_dlpack(obj))
