"""Deterministic fault injection for resilience testing.

Production failure paths (device step errors, allocator exhaustion,
host-transfer hiccups, poisoned logits) are rare and non-reproducible
in the wild — so the serving stack's recovery code would otherwise ship
untested. This registry makes failures first-class test inputs: code
threads named *sites* through its hot paths (``should_fire(site)`` /
``fault_point(site)``), and a test or an operator arms a schedule that
fires at exact call counts or with a seeded probability. The same
schedule always produces the same failure sequence, so a chaos test
that caught a leak replays bit-for-bit.

Spec grammar (programmatic via :func:`configure`, or the ``PT_FAULTS``
env var at import, seed from ``PT_FAULTS_SEED``)::

    site:key=val[,key=val...][;site2:...]

    at=N      fire on the Nth call to the site (1-based)
    every=N   fire on every Nth call
    p=F       fire each call with probability F (seeded, deterministic)
    times=K   stop after K fires (default: unlimited)

e.g. ``PT_FAULTS="serving.step_block:at=3;serving.allocate:p=0.1,times=2"``.

Zero overhead when disarmed: every entry point checks one module-level
bool first; no site bookkeeping, no RNG draw, no dict lookup happens
unless a schedule is armed. The serving tests pin the stronger claim —
compile counts and greedy streams are bit-identical with the module
imported but disarmed.

Sites currently threaded (regenerated with the fleet + network
transport sites; tests/test_fleet_failover.py asserts every armed site
in the tree appears here):

====================== ============================== ==================
site                   fires in                       failure simulated
====================== ============================== ==================
server.tick            Server.run_until_idle          whole tick skipped
serving.step_block     engine/spec step dispatch      device step error
serving.harvest        engine/spec pending-harvest    host transfer loss
serving.prefill_tick   paged chunked prefill          chunk dispatch err
serving.allocate       BlockManager.allocate          pool exhaustion
serving.poison         engine/spec step (KV NaN)      poisoned slot
fleet.serialize        handoff.encode_handoff         serializer crash
fleet.transport        Transport.send (both kinds)    wire refuses send
fleet.adopt            DecodeWorker.adopt             adopt-side crash
fleet.fetch            Fleet._fetch_prefix op         fetch-op crash
fleet.directory        Fleet._beat_one publish        one publish lost
fleet.scale            Fleet add/drain/remove decode  scale action fails
transport.partial_write SocketTransport frame write   torn TCP write
transport.corrupt      SocketTransport frame write    flipped wire byte
transport.disconnect   SocketTransport ack wait       ack loss/conn drop
journal.write          WriteAheadJournal.append       journal IO error
journal.torn_tail      WriteAheadJournal.append       crash mid-append
checkpoint.commit      durability.write_manifest      die before commit
spill.read             PrefixSpillStore.read          spill file unread
====================== ============================== ==================
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np

from ..observability import metrics as _om
from .flags import env_int, env_str

__all__ = ["InjectedFault", "configure", "clear", "active",
           "should_fire", "fault_point", "site_stats", "injected"]

# registered up front so the catalog shows the family even before the
# first fire; inc() only ever runs on the (rare) armed-and-fired path,
# so the disarmed zero-overhead contract is untouched
_M_FIRES = _om.counter("pt_fault_fires_total",
                       "injected fault fires by site", labels=("site",))


class InjectedFault(RuntimeError):
    """Raised by an armed fault site. The resilience layer treats it as
    a transient failure (retry / shed / break the circuit); anything
    else letting it escape is a test finding."""


@dataclass
class _Site:
    name: str
    at: Optional[int] = None        # fire on this 1-based call count
    every: Optional[int] = None     # fire when calls % every == 0
    p: float = 0.0                  # per-call probability (seeded)
    times: Optional[int] = None     # max fires (None = unlimited)
    calls: int = 0
    fires: int = 0
    rng: np.random.RandomState = field(
        default_factory=lambda: np.random.RandomState(0))

    def fire(self) -> bool:
        self.calls += 1
        if self.times is not None and self.fires >= self.times:
            return False
        hit = ((self.at is not None and self.calls == self.at)
               or (self.every is not None
                   and self.calls % self.every == 0)
               or (self.p > 0.0 and self.rng.random_sample() < self.p))
        if hit:
            self.fires += 1
            _M_FIRES.inc(site=self.name)
        return hit


_ARMED = False
_SITES: Dict[str, _Site] = {}


def _parse_spec(spec: str, seed: int) -> Dict[str, _Site]:
    sites: Dict[str, _Site] = {}
    for i, part in enumerate(p for p in spec.split(";") if p.strip()):
        name, _, kvs = part.partition(":")
        name = name.strip()
        if not name or not kvs.strip():
            raise ValueError(
                f"bad fault spec {part!r}: want 'site:key=val[,...]'")
        # each site draws from its own stream so adding a site never
        # shifts another site's firing sequence
        site = _Site(name=name,
                     rng=np.random.RandomState((seed, i)))
        for kv in kvs.split(","):
            k, _, v = kv.partition("=")
            k, v = k.strip(), v.strip()
            if k == "at":
                site.at = int(v)
            elif k == "every":
                site.every = int(v)
            elif k == "p":
                site.p = float(v)
            elif k == "times":
                site.times = int(v)
            else:
                raise ValueError(f"unknown fault spec key {k!r} in "
                                 f"{part!r} (want at/every/p/times)")
        sites[name] = site
    return sites


def configure(spec: str, seed: int = 0):
    """Arm a fault schedule (replacing any previous one). An empty spec
    disarms — equivalent to :func:`clear`."""
    global _ARMED, _SITES
    _SITES = _parse_spec(spec, seed) if spec and spec.strip() else {}
    _ARMED = bool(_SITES)


def clear():
    """Disarm every site (the zero-overhead state)."""
    global _ARMED, _SITES
    _ARMED = False
    _SITES = {}


def active() -> bool:
    return _ARMED


def should_fire(site: str) -> bool:
    """True if the armed schedule says this call of ``site`` fails.
    The caller decides the failure semantics (raise, return None,
    corrupt a value). First line is the disarmed fast path."""
    if not _ARMED:
        return False
    s = _SITES.get(site)
    return s.fire() if s is not None else False


def fault_point(site: str):
    """Raise :class:`InjectedFault` when the schedule fires ``site`` —
    the one-liner for raise-style sites."""
    if _ARMED and should_fire(site):
        raise InjectedFault(f"injected fault at site {site!r} "
                            f"(call {_SITES[site].calls})")


def site_stats() -> Dict[str, Dict[str, int]]:
    """Per-site ``{"calls": n, "fires": m}`` of the armed schedule."""
    return {name: {"calls": s.calls, "fires": s.fires}
            for name, s in _SITES.items()}


class injected:
    """Context manager for tests: arm a schedule, disarm on exit.

    >>> with faults.injected("serving.step_block:at=2"):
    ...     server.run_until_idle()
    """

    def __init__(self, spec: str, seed: int = 0):
        self.spec, self.seed = spec, seed

    def __enter__(self):
        configure(self.spec, self.seed)
        return self

    def __exit__(self, *exc):
        clear()
        return False


# env arming (bench children, operators): PT_FAULTS="site:spec;..."
_env_spec = env_str("PT_FAULTS")
if _env_spec:
    configure(_env_spec, env_int("PT_FAULTS_SEED", 0))
