"""FFT namespace (``paddle.fft`` parity).

Reference parity: python/paddle/fft.py (fft/ifft/rfft/... over cuFFT —
verify). TPU-native: jnp.fft lowers to XLA's FFT HLO; complex64 is the
working dtype on TPU. All entry points tape through ``apply_op`` so they
differentiate in eager mode and fuse under jit.
"""
from __future__ import annotations

import jax.numpy as jnp

from .tensor import Tensor, apply_op

__all__ = [
    "fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
    "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
    "hfft", "ihfft", "hfft2", "ihfft2", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm):
    return None if norm in (None, "backward") else norm


def _mk1(jfn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op(lambda v: jfn(v, n=n, axis=axis, norm=_norm(norm)),
                        x)
    return op


def _mk2(jfn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return apply_op(lambda v: jfn(v, s=s, axes=tuple(axes),
                                      norm=_norm(norm)), x)
    return op


def _mkn(jfn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return apply_op(
            lambda v: jfn(v, s=s, axes=None if axes is None
                          else tuple(axes), norm=_norm(norm)), x)
    return op


fft = _mk1(jnp.fft.fft)
ifft = _mk1(jnp.fft.ifft)
rfft = _mk1(jnp.fft.rfft)
irfft = _mk1(jnp.fft.irfft)
hfft = _mk1(jnp.fft.hfft)
ihfft = _mk1(jnp.fft.ihfft)

fft2 = _mk2(jnp.fft.fft2)
ifft2 = _mk2(jnp.fft.ifft2)
rfft2 = _mk2(jnp.fft.rfft2)
irfft2 = _mk2(jnp.fft.irfft2)

fftn = _mkn(jnp.fft.fftn)
ifftn = _mkn(jnp.fft.ifftn)
rfftn = _mkn(jnp.fft.rfftn)
irfftn = _mkn(jnp.fft.irfftn)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    y = ifftn(x, s=None if s is None else tuple(s[:-1]) + (None,),
              axes=tuple(axes)[:-1], norm=norm)
    return hfft(y, n=None if s is None else s[-1], axis=tuple(axes)[-1],
                norm=norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    y = ihfft(x, n=None if s is None else s[-1], axis=tuple(axes)[-1],
              norm=norm)
    return fftn(y, axes=tuple(axes)[:-1], norm=norm)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    if axes is None:
        axes = tuple(range(-x.ndim, 0))
    y = ifftn(x, axes=tuple(axes)[:-1], norm=norm)
    return hfft(y, n=None if s is None else s[-1], axis=tuple(axes)[-1],
                norm=norm)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    if axes is None:
        axes = tuple(range(-x.ndim, 0))
    y = ihfft(x, n=None if s is None else s[-1], axis=tuple(axes)[-1],
              norm=norm)
    return fftn(y, axes=tuple(axes)[:-1], norm=norm)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or jnp.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or jnp.float32))


def fftshift(x, axes=None, name=None):
    return apply_op(lambda v: jnp.fft.fftshift(v, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return apply_op(lambda v: jnp.fft.ifftshift(v, axes=axes), x)
