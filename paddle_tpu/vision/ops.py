"""Detection ops (reference: python/paddle/vision/ops.py — nms,
roi_align, roi_pool, box_coder, DeformConv2D, yolo_box … — verify).

TPU-native design: everything is static-shaped so it compiles once.
``nms`` returns a fixed-length index vector (padded with -1) driven by a
`lax.fori_loop` greedy suppression — the reference returns a dynamic
count, which cannot exist inside an XLA program; callers mask on >= 0.
roi_align is gather+bilinear arithmetic (MXU-adjacent, fuses into the
surrounding program) rather than a custom CUDA kernel.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor, apply_op

__all__ = ["box_iou", "nms", "roi_align", "roi_pool", "box_coder",
           "RoIAlign", "RoIPool"]


def _iou_matrix(boxes_a, boxes_b):
    """(N, 4) x (M, 4) xyxy → (N, M) IoU (pure jnp)."""
    area_a = (boxes_a[:, 2] - boxes_a[:, 0]) * (boxes_a[:, 3] - boxes_a[:, 1])
    area_b = (boxes_b[:, 2] - boxes_b[:, 0]) * (boxes_b[:, 3] - boxes_b[:, 1])
    lt = jnp.maximum(boxes_a[:, None, :2], boxes_b[None, :, :2])
    rb = jnp.minimum(boxes_a[:, None, 2:], boxes_b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter,
                               1e-9)


def box_iou(boxes1, boxes2, name=None):
    """Pairwise IoU of two xyxy box sets: (N, 4), (M, 4) → (N, M)."""
    return apply_op(_iou_matrix, boxes1, boxes2)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Greedy NMS. Returns kept indices sorted by descending score,
    fixed length (padded with -1 when fewer survive; sliced to ``top_k``
    when given). With ``category_idxs``/``categories``, suppression is
    per-category (batched NMS via the coordinate-offset trick)."""
    n = int(boxes.shape[0])

    def f(bx, *rest):
        it = iter(rest)
        sc = next(it) if scores is not None else jnp.zeros((n,))
        if category_idxs is not None:
            cat = next(it).astype(jnp.float32)
            # disjoint coordinate islands per category: cross-category
            # IoU becomes 0, one suppression pass handles all classes
            # (shift to 0 first so negative coords can't overlap islands)
            lo = jnp.min(bx)
            span = jnp.max(bx) - lo + 1.0
            bx = (bx - lo) + (cat * span)[:, None]
        order = jnp.argsort(-sc)
        bx_sorted = bx[order]
        iou = _iou_matrix(bx_sorted, bx_sorted)

        def body(i, keep):
            # suppress j>i overlapping a KEPT i
            sup = (iou[i] > iou_threshold) & keep[i] & \
                (jnp.arange(n) > i)
            return keep & ~sup
        keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
        kept_sorted = jnp.where(keep, order, -1)
        # stable-compact: kept first (in score order), -1 padding after
        rank = jnp.where(keep, jnp.arange(n), n)
        perm = jnp.argsort(rank)
        return kept_sorted[perm]

    args = [boxes]
    if scores is not None:
        args.append(scores)
    if category_idxs is not None:
        args.append(category_idxs)
    out = apply_op(f, *args)
    if top_k is not None:
        out = apply_op(lambda v: v[:top_k], out)
    return out


def _roi_align_one(feat, roi, out_h, out_w, spatial_scale, sampling_ratio,
                   aligned):
    """feat: (C, H, W); roi: (4,) xyxy in input coords → (C, oh, ow)."""
    c, h, w = feat.shape
    off = 0.5 if aligned else 0.0
    x0 = roi[0] * spatial_scale - off
    y0 = roi[1] * spatial_scale - off
    x1 = roi[2] * spatial_scale - off
    y1 = roi[3] * spatial_scale - off
    rw = jnp.maximum(x1 - x0, 1e-3 if aligned else 1.0)
    rh = jnp.maximum(y1 - y0, 1e-3 if aligned else 1.0)
    bin_h = rh / out_h
    bin_w = rw / out_w
    # reference semantics for sampling_ratio<=0 are ADAPTIVE
    # (ceil(bin_size) samples per bin) — data-dependent shapes that XLA
    # cannot compile; this TPU-native port uses a fixed grid instead
    # (default 2, override via sampling_ratio for wide-RoI fidelity)
    ns = sampling_ratio if sampling_ratio > 0 else 2
    # sample grid: (oh, ns) x (ow, ns) bilinear points, averaged per bin
    iy = y0 + (jnp.arange(out_h)[:, None] + (jnp.arange(ns)[None, :] + .5)
               / ns) * bin_h                       # (oh, ns)
    ix = x0 + (jnp.arange(out_w)[:, None] + (jnp.arange(ns)[None, :] + .5)
               / ns) * bin_w                       # (ow, ns)

    def bilinear(yy, xx):
        # reference contract: samples beyond [-1, size] contribute ZERO
        # (not border replication)
        ok_y = (yy > -1.0) & (yy < h)
        ok_x = (xx > -1.0) & (xx < w)
        yy = jnp.clip(yy, 0, h - 1)
        xx = jnp.clip(xx, 0, w - 1)
        yl = jnp.floor(yy).astype(jnp.int32)
        xl = jnp.floor(xx).astype(jnp.int32)
        yh_ = jnp.minimum(yl + 1, h - 1)
        xh_ = jnp.minimum(xl + 1, w - 1)
        wy = yy - yl
        wx = xx - xl
        v00 = feat[:, yl, :][:, :, xl]
        v01 = feat[:, yl, :][:, :, xh_]
        v10 = feat[:, yh_, :][:, :, xl]
        v11 = feat[:, yh_, :][:, :, xh_]
        out = (v00 * (1 - wy[None, :, None]) * (1 - wx[None, None, :])
               + v01 * (1 - wy[None, :, None]) * wx[None, None, :]
               + v10 * wy[None, :, None] * (1 - wx[None, None, :])
               + v11 * wy[None, :, None] * wx[None, None, :])
        return out * (ok_y[None, :, None] & ok_x[None, None, :])

    ys = iy.reshape(-1)                 # (oh*ns,)
    xs = ix.reshape(-1)                 # (ow*ns,)
    vals = bilinear(ys, xs)             # (C, oh*ns, ow*ns)
    vals = vals.reshape(c, out_h, ns, out_w, ns)
    return jnp.mean(vals, axis=(2, 4))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign over NCHW features. boxes: (R, 4) xyxy; boxes_num: (B,)
    rois per image (static routing via searchsorted)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(feat, bx, bn):
        csum = jnp.cumsum(bn)
        img_of_roi = jnp.searchsorted(csum, jnp.arange(bx.shape[0]),
                                      side="right")
        feats = feat[img_of_roi]        # (R, C, H, W)
        return jax.vmap(lambda fo, ro: _roi_align_one(
            fo, ro, oh, ow, spatial_scale, sampling_ratio, aligned))(
            feats, bx)
    return apply_op(f, x, boxes, boxes_num)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """Max-pool RoI pooling (the older, quantized variant)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(feat, bx, bn):
        b, c, h, w = feat.shape
        csum = jnp.cumsum(bn)
        img_of_roi = jnp.searchsorted(csum, jnp.arange(bx.shape[0]),
                                      side="right")
        feats = feat[img_of_roi]

        def one(fo, roi):
            # classic Fast-R-CNN convention: rounded, INCLUSIVE ends
            x0 = jnp.round(roi[0] * spatial_scale).astype(jnp.int32)
            y0 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
            x1 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
            y1 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
            rh = jnp.maximum(y1 - y0 + 1, 1)
            rw = jnp.maximum(x1 - x0 + 1, 1)
            ys = y0 + (jnp.arange(oh)[:, None] * rh) // oh
            ye = y0 + ((jnp.arange(oh)[:, None] + 1) * rh + oh - 1) // oh
            xs = x0 + (jnp.arange(ow)[None, :] * rw) // ow
            xe = x0 + ((jnp.arange(ow)[None, :] + 1) * rw + ow - 1) // ow
            # evaluate on a dense grid with -inf outside each bin
            yy = jnp.arange(h)
            xx = jnp.arange(w)
            in_y = (yy[None, None, :] >= ys[..., None]) & \
                (yy[None, None, :] < ye[..., None])      # (oh,1,H)
            in_x = (xx[None, None, :] >= xs[..., None]) & \
                (xx[None, None, :] < xe[..., None])      # (1,ow,W)
            mask = in_y[:, :, :, None] & in_x[:, :, None, :]  # (oh,ow,H,W)
            vals = jnp.where(mask[None], fo[:, None, None], -jnp.inf)
            out = jnp.max(vals, axis=(3, 4))
            # bins entirely outside the map (roi past the image edge)
            # pool to 0, matching the reference's clamped-bin behavior
            return jnp.where(jnp.isfinite(out), out, 0.0)
        return jax.vmap(one)(feats, bx)
    return apply_op(f, x, boxes, boxes_num)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None):
    """Encode/decode boxes against priors (reference box_coder op)."""
    if code_type not in ("encode_center_size", "decode_center_size"):
        raise ValueError(
            f"code_type must be 'encode_center_size' or "
            f"'decode_center_size', got {code_type!r}")
    def f(pb, pbv, tb):
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                             jnp.log(tw / pw), jnp.log(th / ph)], axis=1)
            return out / pbv
        d = tb * pbv
        cx = d[:, 0] * pw + pcx
        cy = d[:, 1] * ph + pcy
        w_ = jnp.exp(d[:, 2]) * pw
        h_ = jnp.exp(d[:, 3]) * ph
        return jnp.stack([cx - w_ / 2, cy - h_ / 2,
                          cx + w_ / 2 - norm, cy + h_ / 2 - norm], axis=1)
    return apply_op(f, prior_box, prior_box_var, target_box)


class RoIAlign:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)
