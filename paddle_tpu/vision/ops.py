"""Detection ops (reference: python/paddle/vision/ops.py — nms,
roi_align, roi_pool, box_coder, DeformConv2D, yolo_box … — verify).

TPU-native design: everything is static-shaped so it compiles once.
``nms`` returns a fixed-length index vector (padded with -1) driven by a
`lax.fori_loop` greedy suppression — the reference returns a dynamic
count, which cannot exist inside an XLA program; callers mask on >= 0.
roi_align is gather+bilinear arithmetic (MXU-adjacent, fuses into the
surrounding program) rather than a custom CUDA kernel.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor, apply_op

__all__ = ["box_iou", "nms", "roi_align", "roi_pool", "box_coder",
           "RoIAlign", "RoIPool"]


def _iou_matrix(boxes_a, boxes_b):
    """(N, 4) x (M, 4) xyxy → (N, M) IoU (pure jnp)."""
    area_a = (boxes_a[:, 2] - boxes_a[:, 0]) * (boxes_a[:, 3] - boxes_a[:, 1])
    area_b = (boxes_b[:, 2] - boxes_b[:, 0]) * (boxes_b[:, 3] - boxes_b[:, 1])
    lt = jnp.maximum(boxes_a[:, None, :2], boxes_b[None, :, :2])
    rb = jnp.minimum(boxes_a[:, None, 2:], boxes_b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter,
                               1e-9)


def box_iou(boxes1, boxes2, name=None):
    """Pairwise IoU of two xyxy box sets: (N, 4), (M, 4) → (N, M)."""
    return apply_op(_iou_matrix, boxes1, boxes2)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Greedy NMS. Returns kept indices sorted by descending score,
    fixed length (padded with -1 when fewer survive; sliced to ``top_k``
    when given). With ``category_idxs``/``categories``, suppression is
    per-category (batched NMS via the coordinate-offset trick)."""
    n = int(boxes.shape[0])

    def f(bx, *rest):
        it = iter(rest)
        sc = next(it) if scores is not None else jnp.zeros((n,))
        if category_idxs is not None:
            cat = next(it).astype(jnp.float32)
            # disjoint coordinate islands per category: cross-category
            # IoU becomes 0, one suppression pass handles all classes
            # (shift to 0 first so negative coords can't overlap islands)
            lo = jnp.min(bx)
            span = jnp.max(bx) - lo + 1.0
            bx = (bx - lo) + (cat * span)[:, None]
        order = jnp.argsort(-sc)
        bx_sorted = bx[order]
        iou = _iou_matrix(bx_sorted, bx_sorted)

        def body(i, keep):
            # suppress j>i overlapping a KEPT i
            sup = (iou[i] > iou_threshold) & keep[i] & \
                (jnp.arange(n) > i)
            return keep & ~sup
        keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
        kept_sorted = jnp.where(keep, order, -1)
        # stable-compact: kept first (in score order), -1 padding after
        rank = jnp.where(keep, jnp.arange(n), n)
        perm = jnp.argsort(rank)
        return kept_sorted[perm]

    args = [boxes]
    if scores is not None:
        args.append(scores)
    if category_idxs is not None:
        args.append(category_idxs)
    out = apply_op(f, *args)
    if top_k is not None:
        out = apply_op(lambda v: v[:top_k], out)
    return out


def _roi_align_one(feat, roi, out_h, out_w, spatial_scale, sampling_ratio,
                   aligned):
    """feat: (C, H, W); roi: (4,) xyxy in input coords → (C, oh, ow)."""
    c, h, w = feat.shape
    off = 0.5 if aligned else 0.0
    x0 = roi[0] * spatial_scale - off
    y0 = roi[1] * spatial_scale - off
    x1 = roi[2] * spatial_scale - off
    y1 = roi[3] * spatial_scale - off
    rw = jnp.maximum(x1 - x0, 1e-3 if aligned else 1.0)
    rh = jnp.maximum(y1 - y0, 1e-3 if aligned else 1.0)
    bin_h = rh / out_h
    bin_w = rw / out_w
    # reference semantics for sampling_ratio<=0 are ADAPTIVE
    # (ceil(bin_size) samples per bin) — data-dependent shapes that XLA
    # cannot compile; this TPU-native port uses a fixed grid instead
    # (default 2, override via sampling_ratio for wide-RoI fidelity)
    ns = sampling_ratio if sampling_ratio > 0 else 2
    # sample grid: (oh, ns) x (ow, ns) bilinear points, averaged per bin
    iy = y0 + (jnp.arange(out_h)[:, None] + (jnp.arange(ns)[None, :] + .5)
               / ns) * bin_h                       # (oh, ns)
    ix = x0 + (jnp.arange(out_w)[:, None] + (jnp.arange(ns)[None, :] + .5)
               / ns) * bin_w                       # (ow, ns)

    def bilinear(yy, xx):
        # reference contract: samples beyond [-1, size] contribute ZERO
        # (not border replication)
        ok_y = (yy > -1.0) & (yy < h)
        ok_x = (xx > -1.0) & (xx < w)
        yy = jnp.clip(yy, 0, h - 1)
        xx = jnp.clip(xx, 0, w - 1)
        yl = jnp.floor(yy).astype(jnp.int32)
        xl = jnp.floor(xx).astype(jnp.int32)
        yh_ = jnp.minimum(yl + 1, h - 1)
        xh_ = jnp.minimum(xl + 1, w - 1)
        wy = yy - yl
        wx = xx - xl
        v00 = feat[:, yl, :][:, :, xl]
        v01 = feat[:, yl, :][:, :, xh_]
        v10 = feat[:, yh_, :][:, :, xl]
        v11 = feat[:, yh_, :][:, :, xh_]
        out = (v00 * (1 - wy[None, :, None]) * (1 - wx[None, None, :])
               + v01 * (1 - wy[None, :, None]) * wx[None, None, :]
               + v10 * wy[None, :, None] * (1 - wx[None, None, :])
               + v11 * wy[None, :, None] * wx[None, None, :])
        return out * (ok_y[None, :, None] & ok_x[None, None, :])

    ys = iy.reshape(-1)                 # (oh*ns,)
    xs = ix.reshape(-1)                 # (ow*ns,)
    vals = bilinear(ys, xs)             # (C, oh*ns, ow*ns)
    vals = vals.reshape(c, out_h, ns, out_w, ns)
    return jnp.mean(vals, axis=(2, 4))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign over NCHW features. boxes: (R, 4) xyxy; boxes_num: (B,)
    rois per image (static routing via searchsorted)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(feat, bx, bn):
        csum = jnp.cumsum(bn)
        img_of_roi = jnp.searchsorted(csum, jnp.arange(bx.shape[0]),
                                      side="right")
        feats = feat[img_of_roi]        # (R, C, H, W)
        return jax.vmap(lambda fo, ro: _roi_align_one(
            fo, ro, oh, ow, spatial_scale, sampling_ratio, aligned))(
            feats, bx)
    return apply_op(f, x, boxes, boxes_num)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """Max-pool RoI pooling (the older, quantized variant)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(feat, bx, bn):
        b, c, h, w = feat.shape
        csum = jnp.cumsum(bn)
        img_of_roi = jnp.searchsorted(csum, jnp.arange(bx.shape[0]),
                                      side="right")
        feats = feat[img_of_roi]

        def one(fo, roi):
            # classic Fast-R-CNN convention: rounded, INCLUSIVE ends
            x0 = jnp.round(roi[0] * spatial_scale).astype(jnp.int32)
            y0 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
            x1 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
            y1 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
            rh = jnp.maximum(y1 - y0 + 1, 1)
            rw = jnp.maximum(x1 - x0 + 1, 1)
            ys = y0 + (jnp.arange(oh) * rh) // oh
            ye = y0 + ((jnp.arange(oh) + 1) * rh + oh - 1) // oh
            xs = x0 + (jnp.arange(ow) * rw) // ow
            xe = x0 + ((jnp.arange(ow) + 1) * rw + ow - 1) // ow
            # max over a rectangle is separable: rows first, then cols —
            # peak temp is one (C, H, W) masked copy per sequential bin
            # instead of the (C, oh, ow, H, W) bin-mask outer product
            yy = jnp.arange(h)
            xx = jnp.arange(w)

            def row_bin(i):
                m = (yy >= ys[i]) & (yy < ye[i])
                return jnp.max(jnp.where(m[None, :, None], fo, -jnp.inf),
                               axis=1)                      # (C, W)
            rows = jax.lax.map(row_bin, jnp.arange(oh))     # (oh, C, W)

            def col_bin(j):
                m = (xx >= xs[j]) & (xx < xe[j])
                return jnp.max(jnp.where(m[None, None, :], rows, -jnp.inf),
                               axis=2)                      # (ow->, oh, C)
            cols = jax.lax.map(col_bin, jnp.arange(ow))     # (ow, oh, C)
            out = jnp.transpose(cols, (2, 1, 0))            # (C, oh, ow)
            # bins entirely outside the map (roi past the image edge)
            # pool to 0, matching the reference's clamped-bin behavior
            return jnp.where(jnp.isfinite(out), out, 0.0)
        return jax.vmap(one)(feats, bx)
    return apply_op(f, x, boxes, boxes_num)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None):
    """Encode/decode boxes against priors (reference box_coder op)."""
    if code_type not in ("encode_center_size", "decode_center_size"):
        raise ValueError(
            f"code_type must be 'encode_center_size' or "
            f"'decode_center_size', got {code_type!r}")
    def f(pb, pbv, tb):
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                             jnp.log(tw / pw), jnp.log(th / ph)], axis=1)
            return out / pbv
        d = tb * pbv
        cx = d[:, 0] * pw + pcx
        cy = d[:, 1] * ph + pcy
        w_ = jnp.exp(d[:, 2]) * pw
        h_ = jnp.exp(d[:, 3]) * ph
        return jnp.stack([cx - w_ / 2, cy - h_ / 2,
                          cx + w_ / 2 - norm, cy + h_ / 2 - norm], axis=1)
    return apply_op(f, prior_box, prior_box_var, target_box)


class RoIAlign:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference: deform_conv2d op —
    verify; v2 when ``mask`` is given). Implemented as bilinear sampling
    at offset-shifted taps followed by a grouped 1x1 contraction — pure
    gather+matmul, so XLA fuses it and the MXU does the contraction.

    x: (N, Cin, H, W); offset: (N, 2*dg*kh*kw, Hout, Wout) in (dy, dx)
    pairs; weight: (Cout, Cin/groups, kh, kw); mask: (N, dg*kh*kw,
    Hout, Wout)."""
    import jax
    import jax.numpy as jnp
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else dilation
    kh, kw = int(weight.shape[2]), int(weight.shape[3])
    dg = deformable_groups

    def f(v, off, w, *extra):
        it = iter(extra)
        b_ = next(it) if bias is not None else None
        m_ = next(it) if mask is not None else None
        n, cin, h, wd = v.shape
        cout = w.shape[0]
        hout = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        wout = (wd + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        # base sampling grid per tap: (kh*kw, hout, wout)
        oy = jnp.arange(hout) * sh - ph
        ox = jnp.arange(wout) * sw - pw
        ky = jnp.arange(kh) * dh
        kx = jnp.arange(kw) * dw
        base_y = oy[None, :, None] + ky.repeat(kw)[:, None, None]
        base_x = ox[None, None, :] + jnp.tile(kx, kh)[:, None, None]
        off = off.reshape(n, dg, kh * kw, 2, hout, wout)
        sy = base_y[None, None] + off[:, :, :, 0]     # (N, dg, K, Ho, Wo)
        sx = base_x[None, None] + off[:, :, :, 1]

        def bilinear(img, yy, xx):
            """img: (N, dg, Cg, H, W); yy/xx: (N, dg, K, Ho, Wo).

            Reference DCN border semantics (dmcn_im2col_bilinear): keep
            FRACTIONAL corner weights and zero only the out-of-range
            CORNERS — a clamp would overweight edge pixels and kill the
            offset gradient at the border."""
            y0f = jnp.floor(yy)
            x0f = jnp.floor(xx)
            wy = yy - y0f
            wx = xx - x0f
            y0 = y0f.astype(jnp.int32)
            x0 = x0f.astype(jnp.int32)

            def gat(yi, xi):
                valid = ((yi >= 0) & (yi < h) & (xi >= 0)
                         & (xi < wd))
                yi = jnp.clip(yi, 0, h - 1)
                xi = jnp.clip(xi, 0, wd - 1)

                def per_ng(im, ys, xs):
                    return im[:, ys, xs]       # (Cg, K, Ho, Wo)
                vals = jax.vmap(jax.vmap(per_ng))(img, yi, xi)
                return vals * valid[:, :, None]
            return (gat(y0, x0) * ((1 - wy) * (1 - wx))[:, :, None]
                    + gat(y0, x0 + 1) * ((1 - wy) * wx)[:, :, None]
                    + gat(y0 + 1, x0) * (wy * (1 - wx))[:, :, None]
                    + gat(y0 + 1, x0 + 1) * (wy * wx)[:, :, None])

        img = v.reshape(n, dg, cin // dg, h, wd)
        sampled = bilinear(img, sy, sx)        # (N, dg, Cg, K, Ho, Wo)
        if m_ is not None:
            mm = m_.reshape(n, dg, 1, kh * kw, hout, wout)
            sampled = sampled * mm
        cols = sampled.reshape(n, cin, kh * kw, hout, wout)
        # grouped contraction with the (Cout, Cin/g, K) kernel
        wg = w.reshape(groups, cout // groups, cin // groups, kh * kw)
        cg = cols.reshape(n, groups, cin // groups, kh * kw, hout, wout)
        out = jnp.einsum("ngckhw,gock->ngohw", cg, wg,
                         preferred_element_type=jnp.float32
                         ).reshape(n, cout, hout, wout).astype(v.dtype)
        if b_ is not None:
            out = out + b_.reshape(1, -1, 1, 1)
        return out

    args = [x, offset, weight]
    if bias is not None:
        args.append(bias)
    if mask is not None:
        args.append(mask)
    return apply_op(f, *args)


def _deform_layer_base():
    from .. import nn
    return nn.Layer


class DeformConv2D(_deform_layer_base()):
    """Layer owning the conv weight (offsets/mask come from a separate
    conv branch, as in the reference API). A real nn.Layer: weight/bias
    register as parameters (optimizers and state_dict see them) and
    weight_attr/bias_attr are honored via create_parameter."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        import numpy as np
        from ..nn import initializer as I
        from ..param_attr import ParamAttr
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        fan_in = in_channels // groups * ks[0] * ks[1]
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, *ks),
            attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.Normal(0.0, np.sqrt(2.0 / fan_in)))
        bias_attr = ParamAttr._to_attr(bias_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=bias_attr or None, is_bias=True)
        self._cfg = (stride, padding, dilation, deformable_groups, groups)

    def forward(self, x, offset, mask=None):
        s, p, d, dg, g = self._cfg
        return deform_conv2d(x, offset, self.weight, self.bias, s, p, d,
                             dg, g, mask)


__all__ += ["deform_conv2d", "DeformConv2D"]


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None,
             scale_x_y=1.0, iou_aware=False, iou_aware_factor=0.5):
    """Decode a YOLOv3 detection head into boxes + per-class scores
    (reference: paddle.vision.ops.yolo_box /
    paddle/fluid/operators/detection/yolo_box_op.* — verify).

    x: (N, A*(5+C), H, W) raw head output with A = len(anchors)//2.
    img_size: (N, 2) int (h, w) per image. Returns
    (boxes (N, H*W*A, 4) in x1y1x2y2 image coords,
     scores (N, H*W*A, C)). Predictions whose objectness confidence is
    below ``conf_thresh`` are zeroed, matching the reference."""
    def f(xv, imgv):
        n, _, h, w = xv.shape
        a = len(anchors) // 2
        anc = jnp.asarray(anchors, jnp.float32).reshape(a, 2)
        if iou_aware:
            ioup = jax.nn.sigmoid(xv[:, :a].reshape(n, a, 1, h, w))
            xv = xv[:, a:]
        pred = xv.reshape(n, a, 5 + class_num, h, w)
        gx = jnp.arange(w, dtype=jnp.float32).reshape(1, 1, 1, w)
        gy = jnp.arange(h, dtype=jnp.float32).reshape(1, 1, h, 1)
        bias = 0.5 * (scale_x_y - 1.0)
        cx = (jax.nn.sigmoid(pred[:, :, 0]) * scale_x_y - bias + gx) / w
        cy = (jax.nn.sigmoid(pred[:, :, 1]) * scale_x_y - bias + gy) / h
        input_h = downsample_ratio * h
        input_w = downsample_ratio * w
        bw = jnp.exp(pred[:, :, 2]) * anc[:, 0].reshape(1, a, 1, 1) \
            / input_w
        bh = jnp.exp(pred[:, :, 3]) * anc[:, 1].reshape(1, a, 1, 1) \
            / input_h
        conf = jax.nn.sigmoid(pred[:, :, 4])
        if iou_aware:
            conf = conf ** (1.0 - iou_aware_factor) * \
                ioup[:, :, 0] ** iou_aware_factor
        cls = jax.nn.sigmoid(pred[:, :, 5:])          # (n,a,C,h,w)
        keep = (conf >= conf_thresh).astype(jnp.float32)
        score = (conf * keep)[:, :, None] * cls       # zero below thresh
        imgh = imgv[:, 0].astype(jnp.float32).reshape(n, 1, 1, 1)
        imgw = imgv[:, 1].astype(jnp.float32).reshape(n, 1, 1, 1)
        x1 = (cx - bw / 2) * imgw
        y1 = (cy - bh / 2) * imgh
        x2 = (cx + bw / 2) * imgw
        y2 = (cy + bh / 2) * imgh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imgw - 1)
            y1 = jnp.clip(y1, 0, imgh - 1)
            x2 = jnp.clip(x2, 0, imgw - 1)
            y2 = jnp.clip(y2, 0, imgh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)   # (n,a,h,w,4)
        boxes = boxes * keep[..., None]
        # reference layout: flatten (a, h, w) -> boxes (n, a*h*w, 4)
        boxes = boxes.reshape(n, a * h * w, 4)
        scores = jnp.moveaxis(score, 2, -1).reshape(n, a * h * w,
                                                    class_num)
        return boxes, scores
    out = apply_op(f, x, img_size)
    return out


__all__ += ["yolo_box"]


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (reference:
    paddle.vision.ops.distribute_fpn_proposals — verify):
    level = floor(refer_level + log2(sqrt(area) / refer_scale)),
    clipped to [min_level, max_level]. Returns (multi_rois: list of
    (Mi, 4) per level, restore_ind (M, 1) mapping concat(multi_rois)
    back to the input order, rois_num_per_level or None).

    Host-side op (data-dependent sizes cannot live under jit — the
    reference's GPU op is likewise a standalone kernel invoked between
    network stages)."""
    import numpy as np
    rois = np.asarray(fpn_rois._value if isinstance(fpn_rois, Tensor)
                      else fpn_rois, np.float32)
    off = 1.0 if pixel_offset else 0.0
    ws = np.maximum(rois[:, 2] - rois[:, 0] + off, 0.0)
    hs = np.maximum(rois[:, 3] - rois[:, 1] + off, 0.0)
    scale = np.sqrt(ws * hs)
    lvl = np.floor(refer_level + np.log2(scale / refer_scale + 1e-8))
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    multi, order = [], []
    for l in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == l)[0]
        multi.append(rois[idx])
        order.append(idx)
    order = np.concatenate(order) if order else np.zeros((0,), np.int64)
    restore = np.empty((len(rois), 1), np.int32)
    restore[order, 0] = np.arange(len(rois), dtype=np.int32)
    multi_t = [Tensor(jnp.asarray(m)) for m in multi]
    restore_t = Tensor(jnp.asarray(restore))
    nums = [Tensor(jnp.asarray(np.asarray([len(m)], np.int32)))
            for m in multi] if rois_num is not None else None
    return multi_t, restore_t, nums


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (reference:
    paddle.vision.ops.psroi_pool / R-FCN — verify): input channels are
    C = output_channels * k * k; output channel c at bin (i, j) AVERAGE-
    pools input channel c*k*k + i*k + j inside that bin. x: (N, C, H, W),
    boxes: (M, 4) x1y1x2y2 in image coords, boxes_num: (N,) rois per
    image. Returns (M, output_channels, k, k)."""
    k = output_size if isinstance(output_size, int) else output_size[0]

    def f(xv, bv, nv):
        n, c, hh, ww = xv.shape
        oc = c // (k * k)
        img_of_box = jnp.repeat(jnp.arange(n), nv, axis=0,
                                total_repeat_length=bv.shape[0])

        def one(b, img_i):
            x1 = b[0] * spatial_scale
            y1 = b[1] * spatial_scale
            x2 = b[2] * spatial_scale
            y2 = b[3] * spatial_scale
            bw = jnp.maximum(x2 - x1, 0.1) / k
            bh = jnp.maximum(y2 - y1, 0.1) / k
            yy = jnp.arange(hh, dtype=jnp.float32)[:, None]
            xx = jnp.arange(ww, dtype=jnp.float32)[None, :]
            feat = xv[img_i]                     # (C, H, W)
            outs = []
            for i in range(k):
                for j in range(k):
                    ys, ye = y1 + i * bh, y1 + (i + 1) * bh
                    xs, xe = x1 + j * bw, x1 + (j + 1) * bw
                    m = ((yy >= jnp.floor(ys)) & (yy < jnp.ceil(ye)) &
                         (xx >= jnp.floor(xs)) & (xx < jnp.ceil(xe))
                         ).astype(xv.dtype)
                    cnt = jnp.maximum(m.sum(), 1.0)
                    ch = jnp.arange(oc) * (k * k) + i * k + j
                    pooled = (feat[ch] * m).sum(axis=(-2, -1)) / cnt
                    outs.append(pooled)
            out = jnp.stack(outs, axis=-1).reshape(oc, k, k)
            return out
        return jax.vmap(one)(bv, img_of_box)
    return apply_op(f, x, boxes, boxes_num)


class PSRoIPool:
    """Layer wrapper over ``psroi_pool`` (reference:
    paddle.vision.ops.PSRoIPool — verify)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


__all__ += ["distribute_fpn_proposals", "psroi_pool", "PSRoIPool"]


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (reference: paddle.vision.ops.yolo_loss /
    detection/yolov3_loss_op.* — verify label-smooth constants).

    x: (N, A*(5+C), H, W) head output for THIS scale (A =
    len(anchor_mask)); gt_box (N, B, 4) normalized center-xywh;
    gt_label (N, B) int; padded gts have w*h == 0. Per the reference:
    each gt is assigned to its best shape-IoU anchor over ALL anchors —
    the gt trains this head only if that anchor is in ``anchor_mask``;
    x/y/obj/cls use sigmoid cross-entropy, w/h use L1, box losses are
    weighted by (2 - gw*gh); negatives whose decoded-box IoU with any
    gt exceeds ``ignore_thresh`` are excluded from objectness loss.
    Returns per-image loss (N,)."""
    anc = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask = list(anchor_mask)
    a = len(mask)

    def bce(logit, target):
        return jnp.maximum(logit, 0) - logit * target + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))

    def f(xv, gb, gl, gs):
        n, _, h, w = xv.shape
        input_w = downsample_ratio * w
        input_h = downsample_ratio * h
        pred = xv.reshape(n, a, 5 + class_num, h, w)
        anc_all = jnp.asarray(anc)                      # (Atot, 2)
        anc_used = jnp.asarray(anc[mask])               # (a, 2)

        gw, gh = gb[..., 2], gb[..., 3]                 # (n, B)
        valid = (gw * gh > 0)
        # shape-only IoU vs every anchor (normalized to input size)
        aw = anc_all[:, 0] / input_w                    # (Atot,)
        ah = anc_all[:, 1] / input_h
        inter = jnp.minimum(gw[..., None], aw) * \
            jnp.minimum(gh[..., None], ah)
        union = gw[..., None] * gh[..., None] + aw * ah - inter
        best = jnp.argmax(inter / (union + 1e-9), axis=-1)  # (n, B)
        # slot in this head (or -1)
        slot = jnp.full_like(best, -1)
        for s, m in enumerate(mask):
            slot = jnp.where(best == m, s, slot)
        assigned = valid & (slot >= 0)

        gi = jnp.clip((gb[..., 0] * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gb[..., 1] * h).astype(jnp.int32), 0, h - 1)
        tx = gb[..., 0] * w - gi
        ty = gb[..., 1] * h - gj
        safe_slot = jnp.clip(slot, 0, a - 1)
        tw = jnp.log(jnp.maximum(
            gw * input_w / anc_used[safe_slot, 0], 1e-9))
        th = jnp.log(jnp.maximum(
            gh * input_h / anc_used[safe_slot, 1], 1e-9))
        box_w = 2.0 - gw * gh

        # scatter per-gt targets onto the (a, h, w) grid (last write
        # wins on collisions, matching a sequential assignment)
        zero = jnp.zeros((n, a, h, w), jnp.float32)
        bidx = jnp.arange(n)[:, None] * 0 + jnp.arange(n)[:, None]

        def put(base, val):
            return base.at[bidx, safe_slot, gj, gi].set(
                jnp.where(assigned, val, base[bidx, safe_slot, gj, gi]))
        obj_t = put(zero, jnp.where(assigned, gs, 0.0))
        tx_t = put(zero, tx)
        ty_t = put(zero, ty)
        tw_t = put(zero, tw)
        th_t = put(zero, th)
        bw_t = put(zero, box_w)
        cls_t = jnp.zeros((n, a, h, w, class_num), jnp.float32)
        pos_lab = 1.0 - 1.0 / class_num if use_label_smooth and \
            class_num > 1 else 1.0
        neg_lab = 1.0 / class_num if use_label_smooth and \
            class_num > 1 else 0.0
        safe_lab = jnp.clip(gl, 0, class_num - 1)
        cls_t = cls_t.at[bidx, safe_slot, gj, gi, safe_lab].set(
            jnp.where(assigned, pos_lab, 0.0))
        pos_mask = (obj_t > 0).astype(jnp.float32)

        # decode predictions for the ignore test (like yolo_box)
        gxg = jnp.arange(w, dtype=jnp.float32).reshape(1, 1, 1, w)
        gyg = jnp.arange(h, dtype=jnp.float32).reshape(1, 1, h, 1)
        bias = 0.5 * (scale_x_y - 1.0)
        px = (jax.nn.sigmoid(pred[:, :, 0]) * scale_x_y - bias + gxg) / w
        py = (jax.nn.sigmoid(pred[:, :, 1]) * scale_x_y - bias + gyg) / h
        pw = jnp.exp(jnp.clip(pred[:, :, 2], -10, 10)) * \
            anc_used[:, 0].reshape(1, a, 1, 1) / input_w
        ph = jnp.exp(jnp.clip(pred[:, :, 3], -10, 10)) * \
            anc_used[:, 1].reshape(1, a, 1, 1) / input_h

        def iou_vs_gts(px, py, pw, ph, gb, valid):
            # (n,a,h,w) vs (n,B): max IoU over valid gts
            px1, px2 = px - pw / 2, px + pw / 2
            py1, py2 = py - ph / 2, py + ph / 2
            gx1 = (gb[..., 0] - gb[..., 2] / 2)
            gx2 = (gb[..., 0] + gb[..., 2] / 2)
            gy1 = (gb[..., 1] - gb[..., 3] / 2)
            gy2 = (gb[..., 1] + gb[..., 3] / 2)
            sh = (slice(None), None, None, None, None)
            ix = jnp.maximum(
                jnp.minimum(px2[..., None], gx2[:, None, None, None]) -
                jnp.maximum(px1[..., None], gx1[:, None, None, None]),
                0)
            iy = jnp.maximum(
                jnp.minimum(py2[..., None], gy2[:, None, None, None]) -
                jnp.maximum(py1[..., None], gy1[:, None, None, None]),
                0)
            inter = ix * iy
            union = (pw * ph)[..., None] + \
                (gb[..., 2] * gb[..., 3])[:, None, None, None] - inter
            iou = inter / (union + 1e-9)
            iou = jnp.where(valid[:, None, None, None], iou, 0.0)
            return iou.max(axis=-1)
        best_iou = iou_vs_gts(px, py, pw, ph, gb, valid)
        ignore = ((best_iou > ignore_thresh) & (pos_mask < 0.5)
                  ).astype(jnp.float32)

        lx = bce(pred[:, :, 0], tx_t) * bw_t * pos_mask
        ly = bce(pred[:, :, 1], ty_t) * bw_t * pos_mask
        lw = jnp.abs(pred[:, :, 2] - tw_t) * bw_t * pos_mask
        lh = jnp.abs(pred[:, :, 3] - th_t) * bw_t * pos_mask
        lobj = bce(pred[:, :, 4], obj_t) * \
            jnp.where(pos_mask > 0, obj_t, 1.0 - ignore)
        cls_target = jnp.where(pos_mask[..., None] > 0,
                               jnp.where(cls_t > 0, cls_t, neg_lab),
                               0.0)
        lcls = bce(jnp.moveaxis(pred[:, :, 5:], 2, -1), cls_target) * \
            pos_mask[..., None]
        per_img = (lx + ly + lw + lh + lobj).sum(axis=(1, 2, 3)) + \
            lcls.sum(axis=(1, 2, 3, 4))
        return per_img

    if gt_score is None:
        gl_arr = gt_label._value if isinstance(gt_label, Tensor) \
            else jnp.asarray(gt_label)
        gt_score = Tensor(jnp.ones(gl_arr.shape, jnp.float32))
    return apply_op(f, x, gt_box, gt_label, gt_score)


__all__ += ["yolo_loss"]


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference:
    paddle.vision.ops.generate_proposals /
    detection/generate_proposals_v2_op.* — verify). Per image: top
    ``pre_nms_top_n`` objectness scores, center-size delta decode
    against anchors (with variances), clip to image, drop boxes smaller
    than ``min_size`` (scaled), greedy NMS, keep ``post_nms_top_n``.

    scores (N, A, H, W); bbox_deltas (N, 4A, H, W); img_size (N, 2)
    (h, w); anchors / variances (..., 4) flattened to (A*H*W, 4).
    Host-side op: proposal counts are data-dependent (the reference's
    GPU kernel likewise returns a LoD).

    Reference behaviors kept: ``min_size`` is clamped to >= 1.0; with
    ``pixel_offset=True`` boxes whose CENTER falls outside the image
    are dropped too; adaptive-threshold NMS (``eta != 1.0``) is not
    implemented and raises rather than silently running plain NMS."""
    import numpy as np

    if eta != 1.0:
        raise NotImplementedError(
            f"generate_proposals: adaptive-threshold NMS (eta={eta}) "
            "is not implemented; use eta=1.0")
    min_size = max(float(min_size), 1.0)

    def _np(t):
        return np.asarray(t._value if isinstance(t, Tensor) else t)

    sc = _np(scores).astype(np.float32)
    bd = _np(bbox_deltas).astype(np.float32)
    im = _np(img_size).astype(np.float32)
    anc = _np(anchors).astype(np.float32).reshape(-1, 4)
    var = _np(variances).astype(np.float32).reshape(-1, 4)
    n, a, h, w = sc.shape
    off = 1.0 if pixel_offset else 0.0

    all_rois, all_probs, nums = [], [], []
    for i in range(n):
        s = sc[i].transpose(1, 2, 0).reshape(-1)        # (H*W*A,)
        d = bd[i].reshape(a, 4, h, w).transpose(2, 3, 0, 1) \
            .reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s_i, d_i = s[order], d[order]
        anc_i, var_i = anc[order], var[order]
        aw = anc_i[:, 2] - anc_i[:, 0] + off
        ah = anc_i[:, 3] - anc_i[:, 1] + off
        acx = anc_i[:, 0] + aw * 0.5
        acy = anc_i[:, 1] + ah * 0.5
        cx = var_i[:, 0] * d_i[:, 0] * aw + acx
        cy = var_i[:, 1] * d_i[:, 1] * ah + acy
        bw = aw * np.exp(np.minimum(var_i[:, 2] * d_i[:, 2], 10.0))
        bh = ah * np.exp(np.minimum(var_i[:, 3] * d_i[:, 3], 10.0))
        x1 = cx - bw * 0.5
        y1 = cy - bh * 0.5
        x2 = cx + bw * 0.5 - off
        y2 = cy + bh * 0.5 - off
        ih, iw = im[i, 0], im[i, 1]
        if pixel_offset:
            # reference FilterBoxes: with the pixel-offset convention a
            # box whose center exceeds the image extent is dropped
            # (cx <= im_w && cy <= im_h). Checked on the DECODED box:
            # post-clip the centers are always inside, which would make
            # the filter dead code — here it actually drops proposals
            # decoded past the edge instead of keeping border slivers.
            bcx = (x1 + x2 + off) / 2.0
            bcy = (y1 + y2 + off) / 2.0
            center_in = (bcx <= iw) & (bcy <= ih)
        else:
            center_in = True
        x1 = np.clip(x1, 0, iw - off)
        y1 = np.clip(y1, 0, ih - off)
        x2 = np.clip(x2, 0, iw - off)
        y2 = np.clip(y2, 0, ih - off)
        keep = ((x2 - x1 + off) >= min_size) & \
            ((y2 - y1 + off) >= min_size) & center_in
        boxes = np.stack([x1, y1, x2, y2], axis=1)[keep]
        s_i = s_i[keep]
        if len(boxes):
            kept = nms(Tensor(jnp.asarray(boxes)),
                       iou_threshold=nms_thresh,
                       scores=Tensor(jnp.asarray(s_i)))
            kept = np.asarray(kept._value)
            kept = kept[kept >= 0][:post_nms_top_n]
            boxes, s_i = boxes[kept], s_i[kept]
        all_rois.append(boxes)
        all_probs.append(s_i.reshape(-1, 1))
        nums.append(len(boxes))
    rois = Tensor(jnp.asarray(np.concatenate(all_rois, axis=0)
                              if all_rois else np.zeros((0, 4))))
    probs = Tensor(jnp.asarray(np.concatenate(all_probs, axis=0)
                               if all_probs else np.zeros((0, 1))))
    if return_rois_num:
        return rois, probs, Tensor(jnp.asarray(np.asarray(nums,
                                                          np.int32)))
    return rois, probs


__all__ += ["generate_proposals"]
