"""Vision models: ResNet family, VGG, MobileNet-ish (reference:
python/paddle/vision/models/resnet.py — verify). NCHW layout; convs hit the
MXU via lax.conv_general_dilated."""
from __future__ import annotations

from ..nn import (AdaptiveAvgPool2D, AvgPool2D, BatchNorm2D, Conv2D, Layer,
                  Linear, MaxPool2D, ReLU, Sequential)

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152", "BasicBlock", "BottleneckBlock", "vgg16", "VGG",
           "LeNet"]


class BasicBlock(Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or BatchNorm2D
        self.conv1 = Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                            bias_attr=False)
        self.bn1 = norm_layer(planes)
        self.relu = ReLU()
        self.conv2 = Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = norm_layer(width)
        self.conv2 = Conv2D(width, width, 3, padding=dilation, stride=stride,
                            groups=groups, dilation=dilation,
                            bias_attr=False)
        self.bn2 = norm_layer(width)
        self.conv3 = Conv2D(width, planes * self.expansion, 1,
                            bias_attr=False)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(Layer):
    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.inplanes = 64
        self.dilation = 1
        self.conv1 = Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                            bias_attr=False)
        self.bn1 = BatchNorm2D(self.inplanes)
        self.relu = ReLU()
        self.maxpool = MaxPool2D(3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = Sequential(
                Conv2D(self.inplanes, planes * block.expansion, 1,
                       stride=stride, bias_attr=False),
                BatchNorm2D(planes * block.expansion))
        layers = [block(self.inplanes, planes, stride, downsample,
                        self.groups, self.base_width)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width))
        return Sequential(*layers)

    def forward(self, x):
        x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ..ops.manipulation import flatten
            x = flatten(x, 1)
            x = self.fc(x)
        return x


def resnet18(pretrained=False, **kwargs):
    return ResNet(BasicBlock, 18, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return ResNet(BasicBlock, 34, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 50, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 101, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 152, **kwargs)


class VGG(Layer):
    def __init__(self, features, num_classes=1000):
        super().__init__()
        from ..nn import Dropout
        self.features = features
        self.avgpool = AdaptiveAvgPool2D((7, 7))
        self.classifier = Sequential(
            Linear(512 * 7 * 7, 4096), ReLU(), Dropout(),
            Linear(4096, 4096), ReLU(), Dropout(),
            Linear(4096, num_classes))

    def forward(self, x):
        from ..ops.manipulation import flatten
        x = self.features(x)
        x = self.avgpool(x)
        x = flatten(x, 1)
        return self.classifier(x)


def _vgg_features(cfg):
    layers = []
    in_c = 3
    for v in cfg:
        if v == "M":
            layers.append(MaxPool2D(2, stride=2))
        else:
            layers += [Conv2D(in_c, v, 3, padding=1), BatchNorm2D(v), ReLU()]
            in_c = v
    return Sequential(*layers)


def vgg16(pretrained=False, batch_norm=True, **kwargs):
    cfg = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
           512, 512, 512, "M", 512, 512, 512, "M"]
    return VGG(_vgg_features(cfg), **kwargs)


class LeNet(Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1), ReLU(),
            MaxPool2D(2, stride=2),
            Conv2D(6, 16, 5, stride=1), ReLU(),
            MaxPool2D(2, stride=2))
        self.fc = Sequential(
            Linear(400, 120), Linear(120, 84), Linear(84, num_classes))

    def forward(self, x):
        from ..ops.manipulation import flatten
        x = self.features(x)
        x = flatten(x, 1)
        return self.fc(x)


# ---------------------------------------------------------------------------
# MobileNetV2 (reference: python/paddle/vision/models/mobilenetv2.py — verify)
# ---------------------------------------------------------------------------

def _make_divisible(v, divisor=8, min_value=None):
    """Round channels to multiples of `divisor` (reference: mobilenetv2.py
    _make_divisible — verify); keeps shapes checkpoint-compatible."""
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _InvertedResidual(Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        from ..nn import ReLU6
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers += [Conv2D(inp, hidden, 1, bias_attr=False),
                       BatchNorm2D(hidden), ReLU6()]
        layers += [Conv2D(hidden, hidden, 3, stride=stride, padding=1,
                          groups=hidden, bias_attr=False),
                   BatchNorm2D(hidden), ReLU6(),
                   Conv2D(hidden, oup, 1, bias_attr=False),
                   BatchNorm2D(oup)]
        self.conv = Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        from ..nn import ReLU6
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        inp = _make_divisible(32 * scale)
        last = _make_divisible(1280 * max(1.0, scale))
        features = [Conv2D(3, inp, 3, stride=2, padding=1, bias_attr=False),
                    BatchNorm2D(inp), ReLU6()]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                features.append(_InvertedResidual(
                    inp, out_c, s if i == 0 else 1, t))
                inp = out_c
        features += [Conv2D(inp, last, 1, bias_attr=False),
                     BatchNorm2D(last), ReLU6()]
        self.features = Sequential(*features)
        self.with_pool = with_pool
        self.pool2d_avg = AdaptiveAvgPool2D(1) if with_pool else None
        self.classifier = Linear(last, num_classes) if num_classes > 0 \
            else None

    def forward(self, x):
        from ..ops.manipulation import flatten
        x = self.features(x)
        if self.pool2d_avg is not None:
            x = self.pool2d_avg(x)
        if self.classifier is not None:
            x = self.classifier(flatten(x, 1))
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)


# ---------------------------------------------------------------------------
# Vision Transformer (reference: python/paddle/vision/models/_vision_
# transformer-alike in ecosystem PaddleClas — verify). Attention rides the
# same scaled_dot_product_attention fast path as the LMs.
# ---------------------------------------------------------------------------

class VisionTransformer(Layer):
    def __init__(self, image_size=224, patch_size=16, embed_dim=768,
                 depth=12, num_heads=12, mlp_ratio=4.0, num_classes=1000,
                 in_channels=3):
        super().__init__()
        from ..nn import LayerNorm
        from ..tensor import Parameter
        import jax.numpy as jnp
        self.patch_embed = Conv2D(in_channels, embed_dim, patch_size,
                                  stride=patch_size)
        n_patches = (image_size // patch_size) ** 2
        self.cls_token = Parameter(jnp.zeros((1, 1, embed_dim),
                                             jnp.float32))
        from ..nn.initializer import Normal
        self.pos_embed = Parameter(
            Normal(std=0.02)((1, n_patches + 1, embed_dim), jnp.float32))
        from ..nn.transformer import TransformerEncoderLayer
        self.blocks = Sequential(*[
            TransformerEncoderLayer(embed_dim, num_heads,
                                    int(embed_dim * mlp_ratio), dropout=0.0,
                                    activation="gelu", normalize_before=True)
            for _ in range(depth)])
        self.norm = LayerNorm(embed_dim)
        self.head = Linear(embed_dim, num_classes)

    def forward(self, x):
        from ..ops.manipulation import concat, reshape, transpose
        from ..ops.creation import zeros
        b = x.shape[0]
        h = self.patch_embed(x)                       # (b, d, gh, gw)
        d = h.shape[1]
        h = reshape(h, (b, d, -1))
        h = transpose(h, (0, 2, 1))                   # (b, n, d)
        cls = self.cls_token + zeros((b, 1, d), dtype=h.dtype)
        h = concat([cls, h], axis=1) + self.pos_embed
        h = self.blocks(h)
        h = self.norm(h)
        return self.head(h[:, 0])


def vit_b_16(pretrained=False, **kwargs):
    return VisionTransformer(patch_size=16, embed_dim=768, depth=12,
                             num_heads=12, **kwargs)


def vit_l_16(pretrained=False, **kwargs):
    return VisionTransformer(patch_size=16, embed_dim=1024, depth=24,
                             num_heads=16, **kwargs)


__all__ += ["MobileNetV2", "mobilenet_v2", "VisionTransformer", "vit_b_16",
            "vit_l_16"]


# ---------------------------------------------------------------------------
# round-2 zoo widening (reference: python/paddle/vision/models/{alexnet,
# squeezenet,densenet,shufflenetv2,mobilenetv1,mobilenetv3,googlenet}.py
# — verify)
# ---------------------------------------------------------------------------

class AlexNet(Layer):
    def __init__(self, num_classes=1000):
        super().__init__()
        from ..nn import Dropout
        self.features = Sequential(
            Conv2D(3, 64, 11, stride=4, padding=2), ReLU(),
            MaxPool2D(3, stride=2),
            Conv2D(64, 192, 5, padding=2), ReLU(),
            MaxPool2D(3, stride=2),
            Conv2D(192, 384, 3, padding=1), ReLU(),
            Conv2D(384, 256, 3, padding=1), ReLU(),
            Conv2D(256, 256, 3, padding=1), ReLU(),
            MaxPool2D(3, stride=2))
        self.avgpool = AdaptiveAvgPool2D((6, 6))
        self.classifier = Sequential(
            Dropout(), Linear(256 * 6 * 6, 4096), ReLU(),
            Dropout(), Linear(4096, 4096), ReLU(),
            Linear(4096, num_classes))

    def forward(self, x):
        from ..ops.manipulation import flatten
        return self.classifier(flatten(self.avgpool(self.features(x)), 1))


def alexnet(pretrained=False, **kwargs):
    return AlexNet(**kwargs)


class _Fire(Layer):
    def __init__(self, in_c, squeeze, e1, e3):
        super().__init__()
        self.squeeze = Sequential(Conv2D(in_c, squeeze, 1), ReLU())
        self.expand1 = Sequential(Conv2D(squeeze, e1, 1), ReLU())
        self.expand3 = Sequential(Conv2D(squeeze, e3, 3, padding=1), ReLU())

    def forward(self, x):
        from ..ops.manipulation import concat
        s = self.squeeze(x)
        return concat([self.expand1(s), self.expand3(s)], axis=1)


class SqueezeNet(Layer):
    def __init__(self, version="1.1", num_classes=1000):
        super().__init__()
        from ..nn import Dropout
        if version == "1.0":
            self.features = Sequential(
                Conv2D(3, 96, 7, stride=2), ReLU(), MaxPool2D(3, stride=2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), MaxPool2D(3, stride=2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                MaxPool2D(3, stride=2), _Fire(512, 64, 256, 256))
        else:
            self.features = Sequential(
                Conv2D(3, 64, 3, stride=2), ReLU(), MaxPool2D(3, stride=2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                MaxPool2D(3, stride=2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                MaxPool2D(3, stride=2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.classifier = Sequential(
            Dropout(), Conv2D(512, num_classes, 1), ReLU(),
            AdaptiveAvgPool2D((1, 1)))

    def forward(self, x):
        from ..ops.manipulation import flatten
        return flatten(self.classifier(self.features(x)), 1)


def squeezenet1_0(pretrained=False, **kwargs):
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet("1.1", **kwargs)


class _DenseLayer(Layer):
    def __init__(self, in_c, growth, bn_size):
        super().__init__()
        self.block = Sequential(
            BatchNorm2D(in_c), ReLU(),
            Conv2D(in_c, bn_size * growth, 1, bias_attr=False),
            BatchNorm2D(bn_size * growth), ReLU(),
            Conv2D(bn_size * growth, growth, 3, padding=1, bias_attr=False))

    def forward(self, x):
        from ..ops.manipulation import concat
        return concat([x, self.block(x)], axis=1)


class DenseNet(Layer):
    def __init__(self, layers=121, growth_rate=32, bn_size=4,
                 num_classes=1000):
        super().__init__()
        cfgs = {121: (6, 12, 24, 16), 161: (6, 12, 36, 24),
                169: (6, 12, 32, 32), 201: (6, 12, 48, 32),
                264: (6, 12, 64, 48)}
        block_cfg = cfgs[layers]
        if layers == 161:
            growth_rate = 48
            init_c = 96
        else:
            init_c = 64
        feats = [Conv2D(3, init_c, 7, stride=2, padding=3, bias_attr=False),
                 BatchNorm2D(init_c), ReLU(), MaxPool2D(3, 2, padding=1)]
        c = init_c
        for i, n in enumerate(block_cfg):
            for _ in range(n):
                feats.append(_DenseLayer(c, growth_rate, bn_size))
                c += growth_rate
            if i != len(block_cfg) - 1:
                feats += [BatchNorm2D(c), ReLU(),
                          Conv2D(c, c // 2, 1, bias_attr=False),
                          AvgPool2D(2, 2)]
                c //= 2
        feats += [BatchNorm2D(c), ReLU()]
        self.features = Sequential(*feats)
        self.avgpool = AdaptiveAvgPool2D((1, 1))
        self.fc = Linear(c, num_classes)

    def forward(self, x):
        from ..ops.manipulation import flatten
        return self.fc(flatten(self.avgpool(self.features(x)), 1))


def densenet121(pretrained=False, **kwargs):
    return DenseNet(121, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return DenseNet(201, **kwargs)


class _ShuffleUnit(Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 2:
            self.branch1 = Sequential(
                Conv2D(in_c, in_c, 3, stride=2, padding=1, groups=in_c,
                       bias_attr=False),
                BatchNorm2D(in_c),
                Conv2D(in_c, branch_c, 1, bias_attr=False),
                BatchNorm2D(branch_c), ReLU())
            in2 = in_c
        else:
            self.branch1 = None
            in2 = branch_c
        self.branch2 = Sequential(
            Conv2D(in2, branch_c, 1, bias_attr=False),
            BatchNorm2D(branch_c), ReLU(),
            Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                   groups=branch_c, bias_attr=False),
            BatchNorm2D(branch_c),
            Conv2D(branch_c, branch_c, 1, bias_attr=False),
            BatchNorm2D(branch_c), ReLU())

    def forward(self, x):
        from ..nn.functional import channel_shuffle
        from ..ops.manipulation import concat, split
        if self.stride == 1:
            x1, x2 = split(x, 2, axis=1)
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


class ShuffleNetV2(Layer):
    def __init__(self, scale=1.0, num_classes=1000):
        super().__init__()
        stage_c = {0.5: (48, 96, 192, 1024), 1.0: (116, 232, 464, 1024),
                   1.5: (176, 352, 704, 1024),
                   2.0: (244, 488, 976, 2048)}[scale]
        self.conv1 = Sequential(
            Conv2D(3, 24, 3, stride=2, padding=1, bias_attr=False),
            BatchNorm2D(24), ReLU())
        self.maxpool = MaxPool2D(3, 2, padding=1)
        stages = []
        in_c = 24
        for i, (c, n) in enumerate(zip(stage_c[:3], (4, 8, 4))):
            units = [_ShuffleUnit(in_c, c, 2)]
            for _ in range(n - 1):
                units.append(_ShuffleUnit(c, c, 1))
            stages.append(Sequential(*units))
            in_c = c
        self.stages = Sequential(*stages)
        self.conv5 = Sequential(
            Conv2D(in_c, stage_c[3], 1, bias_attr=False),
            BatchNorm2D(stage_c[3]), ReLU())
        self.avgpool = AdaptiveAvgPool2D((1, 1))
        self.fc = Linear(stage_c[3], num_classes)

    def forward(self, x):
        from ..ops.manipulation import flatten
        x = self.maxpool(self.conv1(x))
        x = self.conv5(self.stages(x))
        return self.fc(flatten(self.avgpool(x), 1))


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(1.0, **kwargs)


def wide_resnet50_2(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 50, width=128, **kwargs)


def resnext50_32x4d(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 50, width=4, groups=32, **kwargs)


def vgg11(pretrained=False, **kwargs):
    return VGG(_vgg_features(
        [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"]),
        **kwargs)


def vgg13(pretrained=False, **kwargs):
    return VGG(_vgg_features(
        [64, 64, "M", 128, 128, "M", 256, 256, "M",
         512, 512, "M", 512, 512, "M"]), **kwargs)


def vgg19(pretrained=False, **kwargs):
    return VGG(_vgg_features(
        [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"]), **kwargs)


__all__ += ["AlexNet", "alexnet", "SqueezeNet", "squeezenet1_0",
            "squeezenet1_1", "DenseNet", "densenet121", "densenet201",
            "ShuffleNetV2", "shufflenet_v2_x1_0", "wide_resnet50_2",
            "resnext50_32x4d", "vgg11", "vgg13", "vgg19"]


class _SEModule(Layer):
    def __init__(self, c, r=4):
        super().__init__()
        from ..nn import Hardsigmoid
        squeeze = _make_divisible(c // r, 8)
        self.pool = AdaptiveAvgPool2D((1, 1))
        self.fc1 = Conv2D(c, squeeze, 1)
        self.relu = ReLU()
        self.fc2 = Conv2D(squeeze, c, 1)
        self.hsig = Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _MBV3Block(Layer):
    def __init__(self, in_c, exp_c, out_c, k, stride, se, act):
        super().__init__()
        from ..nn import Hardswish
        Act = Hardswish if act == "hs" else ReLU
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if exp_c != in_c:
            layers += [Conv2D(in_c, exp_c, 1, bias_attr=False),
                       BatchNorm2D(exp_c), Act()]
        layers += [Conv2D(exp_c, exp_c, k, stride=stride, padding=k // 2,
                          groups=exp_c, bias_attr=False),
                   BatchNorm2D(exp_c), Act()]
        if se:
            layers.append(_SEModule(exp_c))
        layers += [Conv2D(exp_c, out_c, 1, bias_attr=False),
                   BatchNorm2D(out_c)]
        self.block = Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return out + x if self.use_res else out


_MBV3_LARGE = [
    # k, exp, out, se, act, stride
    (3, 16, 16, False, "re", 1), (3, 64, 24, False, "re", 2),
    (3, 72, 24, False, "re", 1), (5, 72, 40, True, "re", 2),
    (5, 120, 40, True, "re", 1), (5, 120, 40, True, "re", 1),
    (3, 240, 80, False, "hs", 2), (3, 200, 80, False, "hs", 1),
    (3, 184, 80, False, "hs", 1), (3, 184, 80, False, "hs", 1),
    (3, 480, 112, True, "hs", 1), (3, 672, 112, True, "hs", 1),
    (5, 672, 160, True, "hs", 2), (5, 960, 160, True, "hs", 1),
    (5, 960, 160, True, "hs", 1)]
_MBV3_SMALL = [
    (3, 16, 16, True, "re", 2), (3, 72, 24, False, "re", 2),
    (3, 88, 24, False, "re", 1), (5, 96, 40, True, "hs", 2),
    (5, 240, 40, True, "hs", 1), (5, 240, 40, True, "hs", 1),
    (5, 120, 48, True, "hs", 1), (5, 144, 48, True, "hs", 1),
    (5, 288, 96, True, "hs", 2), (5, 576, 96, True, "hs", 1),
    (5, 576, 96, True, "hs", 1)]


class MobileNetV3(Layer):
    """(reference: python/paddle/vision/models/mobilenetv3.py — verify)"""

    def __init__(self, arch="large", num_classes=1000, scale=1.0):
        super().__init__()
        from ..nn import Dropout, Hardswish
        cfg = _MBV3_LARGE if arch == "large" else _MBV3_SMALL
        last_exp = 960 if arch == "large" else 576
        last_c = 1280 if arch == "large" else 1024
        sc = lambda c: _make_divisible(c * scale)
        layers = [Conv2D(3, sc(16), 3, stride=2, padding=1,
                         bias_attr=False),
                  BatchNorm2D(sc(16)), Hardswish()]
        in_c = sc(16)
        for k, exp, out, se, act, stride in cfg:
            layers.append(_MBV3Block(in_c, sc(exp), sc(out), k, stride, se,
                                     act))
            in_c = sc(out)
        layers += [Conv2D(in_c, sc(last_exp), 1, bias_attr=False),
                   BatchNorm2D(sc(last_exp)), Hardswish()]
        self.features = Sequential(*layers)
        self.avgpool = AdaptiveAvgPool2D((1, 1))
        self.classifier = Sequential(
            Linear(sc(last_exp), last_c), Hardswish(), Dropout(0.2),
            Linear(last_c, num_classes))

    def forward(self, x):
        from ..ops.manipulation import flatten
        return self.classifier(flatten(self.avgpool(self.features(x)), 1))


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3("large", scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3("small", scale=scale, **kwargs)


class _Inception(Layer):
    def __init__(self, in_c, c1, c3r, c3, c5r, c5, pp):
        super().__init__()
        self.b1 = Sequential(Conv2D(in_c, c1, 1), ReLU())
        self.b2 = Sequential(Conv2D(in_c, c3r, 1), ReLU(),
                             Conv2D(c3r, c3, 3, padding=1), ReLU())
        self.b3 = Sequential(Conv2D(in_c, c5r, 1), ReLU(),
                             Conv2D(c5r, c5, 5, padding=2), ReLU())
        self.b4 = Sequential(MaxPool2D(3, 1, padding=1),
                             Conv2D(in_c, pp, 1), ReLU())

    def forward(self, x):
        from ..ops.manipulation import concat
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                      axis=1)


class GoogLeNet(Layer):
    """(reference: python/paddle/vision/models/googlenet.py — verify;
    aux classifiers omitted as in inference-mode reference use)"""

    def __init__(self, num_classes=1000):
        super().__init__()
        from ..nn import Dropout
        self.stem = Sequential(
            Conv2D(3, 64, 7, stride=2, padding=3), ReLU(),
            MaxPool2D(3, 2, padding=1),
            Conv2D(64, 64, 1), ReLU(),
            Conv2D(64, 192, 3, padding=1), ReLU(),
            MaxPool2D(3, 2, padding=1))
        self.blocks = Sequential(
            _Inception(192, 64, 96, 128, 16, 32, 32),
            _Inception(256, 128, 128, 192, 32, 96, 64),
            MaxPool2D(3, 2, padding=1),
            _Inception(480, 192, 96, 208, 16, 48, 64),
            _Inception(512, 160, 112, 224, 24, 64, 64),
            _Inception(512, 128, 128, 256, 24, 64, 64),
            _Inception(512, 112, 144, 288, 32, 64, 64),
            _Inception(528, 256, 160, 320, 32, 128, 128),
            MaxPool2D(3, 2, padding=1),
            _Inception(832, 256, 160, 320, 32, 128, 128),
            _Inception(832, 384, 192, 384, 48, 128, 128))
        self.avgpool = AdaptiveAvgPool2D((1, 1))
        self.dropout = None
        self.fc = Linear(1024, num_classes)

    def forward(self, x):
        from ..ops.manipulation import flatten
        x = self.blocks(self.stem(x))
        return self.fc(flatten(self.avgpool(x), 1))


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)


__all__ += ["MobileNetV3", "mobilenet_v3_large", "mobilenet_v3_small",
            "GoogLeNet", "googlenet"]


class _ConvBN(Layer):
    def __init__(self, in_c, out_c, k, stride=1, padding=0):
        super().__init__()
        self.conv = Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                           bias_attr=False)
        self.bn = BatchNorm2D(out_c)
        self.act = ReLU()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class _InceptionA(Layer):
    def __init__(self, in_c, pool_c):
        super().__init__()
        self.b1 = _ConvBN(in_c, 64, 1)
        self.b5 = Sequential(_ConvBN(in_c, 48, 1),
                             _ConvBN(48, 64, 5, padding=2))
        self.b3 = Sequential(_ConvBN(in_c, 64, 1),
                             _ConvBN(64, 96, 3, padding=1),
                             _ConvBN(96, 96, 3, padding=1))
        self.bp = Sequential(AvgPool2D(3, 1, padding=1),
                             _ConvBN(in_c, pool_c, 1))

    def forward(self, x):
        from ..ops.manipulation import concat
        return concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)],
                      axis=1)


class _InceptionB(Layer):           # grid reduction 35 -> 17
    def __init__(self, in_c):
        super().__init__()
        self.b3 = _ConvBN(in_c, 384, 3, stride=2)
        self.bd = Sequential(_ConvBN(in_c, 64, 1),
                             _ConvBN(64, 96, 3, padding=1),
                             _ConvBN(96, 96, 3, stride=2))
        self.pool = MaxPool2D(3, 2)

    def forward(self, x):
        from ..ops.manipulation import concat
        return concat([self.b3(x), self.bd(x), self.pool(x)], axis=1)


class _InceptionC(Layer):           # factorized 7x7
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _ConvBN(in_c, 192, 1)
        self.b7 = Sequential(
            _ConvBN(in_c, c7, 1),
            _ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            _ConvBN(c7, 192, (7, 1), padding=(3, 0)))
        self.b77 = Sequential(
            _ConvBN(in_c, c7, 1),
            _ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            _ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            _ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            _ConvBN(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = Sequential(AvgPool2D(3, 1, padding=1),
                             _ConvBN(in_c, 192, 1))

    def forward(self, x):
        from ..ops.manipulation import concat
        return concat([self.b1(x), self.b7(x), self.b77(x), self.bp(x)],
                      axis=1)


class _InceptionD(Layer):           # grid reduction 17 -> 8
    def __init__(self, in_c):
        super().__init__()
        self.b3 = Sequential(_ConvBN(in_c, 192, 1),
                             _ConvBN(192, 320, 3, stride=2))
        self.b7 = Sequential(_ConvBN(in_c, 192, 1),
                             _ConvBN(192, 192, (1, 7), padding=(0, 3)),
                             _ConvBN(192, 192, (7, 1), padding=(3, 0)),
                             _ConvBN(192, 192, 3, stride=2))
        self.pool = MaxPool2D(3, 2)

    def forward(self, x):
        from ..ops.manipulation import concat
        return concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class _InceptionE(Layer):           # expanded-filter-bank output blocks
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _ConvBN(in_c, 320, 1)
        self.b3_stem = _ConvBN(in_c, 384, 1)
        self.b3_a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.bd_stem = Sequential(_ConvBN(in_c, 448, 1),
                                  _ConvBN(448, 384, 3, padding=1))
        self.bd_a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.bd_b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.bp = Sequential(AvgPool2D(3, 1, padding=1),
                             _ConvBN(in_c, 192, 1))

    def forward(self, x):
        from ..ops.manipulation import concat
        s3 = self.b3_stem(x)
        sd = self.bd_stem(x)
        return concat([self.b1(x),
                       concat([self.b3_a(s3), self.b3_b(s3)], axis=1),
                       concat([self.bd_a(sd), self.bd_b(sd)], axis=1),
                       self.bp(x)], axis=1)


class InceptionV3(Layer):
    """(reference: python/paddle/vision/models/inceptionv3.py — verify;
    aux head omitted as in inference-mode reference use). 299x299 input."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        from ..nn import Dropout
        self.stem = Sequential(
            _ConvBN(3, 32, 3, stride=2), _ConvBN(32, 32, 3),
            _ConvBN(32, 64, 3, padding=1), MaxPool2D(3, 2),
            _ConvBN(64, 80, 1), _ConvBN(80, 192, 3), MaxPool2D(3, 2))
        self.blocks = Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64),
            _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160),
            _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048))
        self.avgpool = AdaptiveAvgPool2D((1, 1))
        self.dropout = Dropout(0.5)
        self.fc = Linear(2048, num_classes)

    def forward(self, x):
        from ..ops.manipulation import flatten
        x = self.blocks(self.stem(x))
        x = self.dropout(flatten(self.avgpool(x), 1))
        return self.fc(x)


def inception_v3(pretrained=False, **kwargs):
    return InceptionV3(**kwargs)


__all__ += ["InceptionV3", "inception_v3"]
