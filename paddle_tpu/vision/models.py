"""Vision models: ResNet family, VGG, MobileNet-ish (reference:
python/paddle/vision/models/resnet.py — verify). NCHW layout; convs hit the
MXU via lax.conv_general_dilated."""
from __future__ import annotations

from ..nn import (AdaptiveAvgPool2D, BatchNorm2D, Conv2D, Layer, Linear,
                  MaxPool2D, ReLU, Sequential)

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152", "BasicBlock", "BottleneckBlock", "vgg16", "VGG",
           "LeNet"]


class BasicBlock(Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or BatchNorm2D
        self.conv1 = Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                            bias_attr=False)
        self.bn1 = norm_layer(planes)
        self.relu = ReLU()
        self.conv2 = Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = norm_layer(width)
        self.conv2 = Conv2D(width, width, 3, padding=dilation, stride=stride,
                            groups=groups, dilation=dilation,
                            bias_attr=False)
        self.bn2 = norm_layer(width)
        self.conv3 = Conv2D(width, planes * self.expansion, 1,
                            bias_attr=False)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(Layer):
    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.inplanes = 64
        self.dilation = 1
        self.conv1 = Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                            bias_attr=False)
        self.bn1 = BatchNorm2D(self.inplanes)
        self.relu = ReLU()
        self.maxpool = MaxPool2D(3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = Sequential(
                Conv2D(self.inplanes, planes * block.expansion, 1,
                       stride=stride, bias_attr=False),
                BatchNorm2D(planes * block.expansion))
        layers = [block(self.inplanes, planes, stride, downsample,
                        self.groups, self.base_width)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width))
        return Sequential(*layers)

    def forward(self, x):
        x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ..ops.manipulation import flatten
            x = flatten(x, 1)
            x = self.fc(x)
        return x


def resnet18(pretrained=False, **kwargs):
    return ResNet(BasicBlock, 18, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return ResNet(BasicBlock, 34, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 50, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 101, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 152, **kwargs)


class VGG(Layer):
    def __init__(self, features, num_classes=1000):
        super().__init__()
        from ..nn import Dropout
        self.features = features
        self.avgpool = AdaptiveAvgPool2D((7, 7))
        self.classifier = Sequential(
            Linear(512 * 7 * 7, 4096), ReLU(), Dropout(),
            Linear(4096, 4096), ReLU(), Dropout(),
            Linear(4096, num_classes))

    def forward(self, x):
        from ..ops.manipulation import flatten
        x = self.features(x)
        x = self.avgpool(x)
        x = flatten(x, 1)
        return self.classifier(x)


def _vgg_features(cfg):
    layers = []
    in_c = 3
    for v in cfg:
        if v == "M":
            layers.append(MaxPool2D(2, stride=2))
        else:
            layers += [Conv2D(in_c, v, 3, padding=1), BatchNorm2D(v), ReLU()]
            in_c = v
    return Sequential(*layers)


def vgg16(pretrained=False, batch_norm=True, **kwargs):
    cfg = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
           512, 512, 512, "M", 512, 512, 512, "M"]
    return VGG(_vgg_features(cfg), **kwargs)


class LeNet(Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1), ReLU(),
            MaxPool2D(2, stride=2),
            Conv2D(6, 16, 5, stride=1), ReLU(),
            MaxPool2D(2, stride=2))
        self.fc = Sequential(
            Linear(400, 120), Linear(120, 84), Linear(84, num_classes))

    def forward(self, x):
        from ..ops.manipulation import flatten
        x = self.features(x)
        x = flatten(x, 1)
        return self.fc(x)


# ---------------------------------------------------------------------------
# MobileNetV2 (reference: python/paddle/vision/models/mobilenetv2.py — verify)
# ---------------------------------------------------------------------------

def _make_divisible(v, divisor=8, min_value=None):
    """Round channels to multiples of `divisor` (reference: mobilenetv2.py
    _make_divisible — verify); keeps shapes checkpoint-compatible."""
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _InvertedResidual(Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        from ..nn import ReLU6
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers += [Conv2D(inp, hidden, 1, bias_attr=False),
                       BatchNorm2D(hidden), ReLU6()]
        layers += [Conv2D(hidden, hidden, 3, stride=stride, padding=1,
                          groups=hidden, bias_attr=False),
                   BatchNorm2D(hidden), ReLU6(),
                   Conv2D(hidden, oup, 1, bias_attr=False),
                   BatchNorm2D(oup)]
        self.conv = Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        from ..nn import ReLU6
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        inp = _make_divisible(32 * scale)
        last = _make_divisible(1280 * max(1.0, scale))
        features = [Conv2D(3, inp, 3, stride=2, padding=1, bias_attr=False),
                    BatchNorm2D(inp), ReLU6()]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                features.append(_InvertedResidual(
                    inp, out_c, s if i == 0 else 1, t))
                inp = out_c
        features += [Conv2D(inp, last, 1, bias_attr=False),
                     BatchNorm2D(last), ReLU6()]
        self.features = Sequential(*features)
        self.with_pool = with_pool
        self.pool2d_avg = AdaptiveAvgPool2D(1) if with_pool else None
        self.classifier = Linear(last, num_classes) if num_classes > 0 \
            else None

    def forward(self, x):
        from ..ops.manipulation import flatten
        x = self.features(x)
        if self.pool2d_avg is not None:
            x = self.pool2d_avg(x)
        if self.classifier is not None:
            x = self.classifier(flatten(x, 1))
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)


# ---------------------------------------------------------------------------
# Vision Transformer (reference: python/paddle/vision/models/_vision_
# transformer-alike in ecosystem PaddleClas — verify). Attention rides the
# same scaled_dot_product_attention fast path as the LMs.
# ---------------------------------------------------------------------------

class VisionTransformer(Layer):
    def __init__(self, image_size=224, patch_size=16, embed_dim=768,
                 depth=12, num_heads=12, mlp_ratio=4.0, num_classes=1000,
                 in_channels=3):
        super().__init__()
        from ..nn import LayerNorm
        from ..tensor import Parameter
        import jax.numpy as jnp
        self.patch_embed = Conv2D(in_channels, embed_dim, patch_size,
                                  stride=patch_size)
        n_patches = (image_size // patch_size) ** 2
        self.cls_token = Parameter(jnp.zeros((1, 1, embed_dim),
                                             jnp.float32))
        from ..nn.initializer import Normal
        self.pos_embed = Parameter(
            Normal(std=0.02)((1, n_patches + 1, embed_dim), jnp.float32))
        from ..nn.transformer import TransformerEncoderLayer
        self.blocks = Sequential(*[
            TransformerEncoderLayer(embed_dim, num_heads,
                                    int(embed_dim * mlp_ratio), dropout=0.0,
                                    activation="gelu", normalize_before=True)
            for _ in range(depth)])
        self.norm = LayerNorm(embed_dim)
        self.head = Linear(embed_dim, num_classes)

    def forward(self, x):
        from ..ops.manipulation import concat, reshape, transpose
        from ..ops.creation import zeros
        b = x.shape[0]
        h = self.patch_embed(x)                       # (b, d, gh, gw)
        d = h.shape[1]
        h = reshape(h, (b, d, -1))
        h = transpose(h, (0, 2, 1))                   # (b, n, d)
        cls = self.cls_token + zeros((b, 1, d), dtype=h.dtype)
        h = concat([cls, h], axis=1) + self.pos_embed
        h = self.blocks(h)
        h = self.norm(h)
        return self.head(h[:, 0])


def vit_b_16(pretrained=False, **kwargs):
    return VisionTransformer(patch_size=16, embed_dim=768, depth=12,
                             num_heads=12, **kwargs)


def vit_l_16(pretrained=False, **kwargs):
    return VisionTransformer(patch_size=16, embed_dim=1024, depth=24,
                             num_heads=16, **kwargs)


__all__ += ["MobileNetV2", "mobilenet_v2", "VisionTransformer", "vit_b_16",
            "vit_l_16"]
