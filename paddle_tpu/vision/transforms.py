"""Vision transforms on numpy/Tensor (reference:
python/paddle/vision/transforms/ — verify)."""
from __future__ import annotations

import numpy as np

from ..tensor import Tensor, to_tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "Transpose", "normalize",
           "to_tensor_fn"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, dtype=np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return to_tensor(arr)


to_tensor_fn = ToTensor


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img._value if isinstance(img, Tensor) else img,
                         dtype=np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        arr = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return to_tensor(arr) if isinstance(img, Tensor) else arr


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)

    def __call__(self, img):
        import jax
        import jax.numpy as jnp
        arr = np.asarray(img._value if isinstance(img, Tensor) else img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        if chw:
            out_shape = (arr.shape[0],) + tuple(self.size)
        elif arr.ndim == 3:
            out_shape = tuple(self.size) + (arr.shape[2],)
        else:
            out_shape = tuple(self.size)
        out = jax.image.resize(jnp.asarray(arr, jnp.float32), out_shape,
                               "linear")
        return to_tensor(out) if isinstance(img, Tensor) else np.asarray(out)


class CenterCrop:
    def __init__(self, size):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)

    def __call__(self, img):
        arr = np.asarray(img._value if isinstance(img, Tensor) else img)
        h_axis = 1 if (arr.ndim == 3 and arr.shape[0] in (1, 3)) else 0
        h, w = arr.shape[h_axis], arr.shape[h_axis + 1]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        sl = [slice(None)] * arr.ndim
        sl[h_axis] = slice(i, i + th)
        sl[h_axis + 1] = slice(j, j + tw)
        out = arr[tuple(sl)]
        return to_tensor(out) if isinstance(img, Tensor) else out


class RandomCrop(CenterCrop):
    def __call__(self, img):
        arr = np.asarray(img._value if isinstance(img, Tensor) else img)
        h_axis = 1 if (arr.ndim == 3 and arr.shape[0] in (1, 3)) else 0
        h, w = arr.shape[h_axis], arr.shape[h_axis + 1]
        th, tw = self.size
        i = np.random.randint(0, max(h - th, 0) + 1)
        j = np.random.randint(0, max(w - tw, 0) + 1)
        sl = [slice(None)] * arr.ndim
        sl[h_axis] = slice(i, i + th)
        sl[h_axis + 1] = slice(j, j + tw)
        out = arr[tuple(sl)]
        return to_tensor(out) if isinstance(img, Tensor) else out


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            arr = np.asarray(img._value if isinstance(img, Tensor) else img)
            out = arr[..., ::-1].copy()
            return to_tensor(out) if isinstance(img, Tensor) else out
        return img


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        arr = np.asarray(img._value if isinstance(img, Tensor) else img)
        out = arr.transpose(self.order)
        return to_tensor(out) if isinstance(img, Tensor) else out


def _arr(img):
    return np.asarray(img._value if isinstance(img, Tensor) else img)


def _ret(out, img):
    return to_tensor(np.ascontiguousarray(out)) \
        if isinstance(img, Tensor) else np.ascontiguousarray(out)


def _hwc_view(arr):
    """(channel-first?, hwc array) — transforms operate in HWC."""
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
    return chw, (arr.transpose(1, 2, 0) if chw else arr)


def _back(out, chw):
    return out.transpose(2, 0, 1) if chw else out


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


class Pad:
    """Constant/edge/reflect padding (reference: transforms.Pad)."""

    def __init__(self, padding, fill=0, padding_mode="constant"):
        if isinstance(padding, int):
            padding = (padding, padding, padding, padding)
        elif len(padding) == 2:
            padding = (padding[0], padding[1], padding[0], padding[1])
        self.padding = padding           # (left, top, right, bottom)
        self.fill = fill
        self.mode = padding_mode

    def __call__(self, img):
        arr = _arr(img)
        chw, hwc = _hwc_view(arr)
        l, t, r, b = self.padding
        pad = ((t, b), (l, r)) + (((0, 0),) if hwc.ndim == 3 else ())
        if self.mode == "constant":
            out = np.pad(hwc, pad, constant_values=self.fill)
        else:
            out = np.pad(hwc, pad, mode=self.mode)
        return _ret(_back(out, chw), img)


class RandomRotation:
    """Rotation by a uniform angle in [-degrees, degrees]; bilinear
    sampling on the HWC grid (reference: transforms.RandomRotation)."""

    def __init__(self, degrees, interpolation="nearest", fill=0):
        if interpolation != "nearest":
            raise NotImplementedError(
                f"RandomRotation(interpolation={interpolation!r}): "
                "only 'nearest' sampling is implemented")
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)
        self.fill = fill

    def __call__(self, img):
        angle = np.random.uniform(*self.degrees)
        arr = _arr(img).astype(np.float32)
        chw, hwc = _hwc_view(arr)
        return _ret(_back(_rotate_nearest(hwc, angle, self.fill), chw),
                    img)


def _rotate_nearest(hwc, angle_deg, fill, center=None):
    """Rotate HWC content counter-clockwise by ``angle_deg`` (nearest
    sampling, same canvas): output(y,x) pulls from the source grid
    rotated the opposite way. rotate(90) == np.rot90(img, 1)."""
    rad = float(angle_deg) * np.pi / 180.0
    h, w = hwc.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None \
        else (center[1], center[0])
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    ys, xs = yy - cy, xx - cx
    cos, sin = np.cos(rad), np.sin(rad)
    # inverse map for CCW-positive visual rotation on the y-down grid
    sy = (cos * ys + sin * xs + cy).round().astype(np.int64)
    sx = (-sin * ys + cos * xs + cx).round().astype(np.int64)
    valid = (sy >= 0) & (sy < h) & (sx >= 0) & (sx < w)
    sy, sx = sy.clip(0, h - 1), sx.clip(0, w - 1)
    out = hwc[sy, sx]
    out[~valid] = fill
    return out


class RandomResizedCrop:
    """Random area/aspect crop then resize (reference:
    transforms.RandomResizedCrop)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation="bilinear"):
        self.size = size if isinstance(size, (tuple, list)) \
            else (size, size)
        self.scale, self.ratio = scale, ratio

    def __call__(self, img):
        arr = _arr(img)
        chw, hwc = _hwc_view(arr)
        h, w = hwc.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                crop = hwc[i:i + ch, j:j + cw]
                break
        else:
            m = min(h, w)
            i, j = (h - m) // 2, (w - m) // 2
            crop = hwc[i:i + m, j:j + m]
        out = Resize(self.size)(_back(crop, chw) if chw else crop)
        return out if isinstance(img, Tensor) == isinstance(out, Tensor) \
            else _ret(np.asarray(out), img)


class Grayscale:
    def __init__(self, num_output_channels=1):
        self.n = num_output_channels

    def __call__(self, img):
        arr = _arr(img).astype(np.float32)
        chw, hwc = _hwc_view(arr)
        if hwc.ndim == 2:
            g = hwc[..., None]
        else:
            g = (hwc[..., :3] @ np.array([0.299, 0.587, 0.114],
                                         np.float32))[..., None]
        out = np.repeat(g, self.n, axis=-1)
        return _ret(_back(out, chw), img)


class BrightnessTransform:
    def __init__(self, value):
        self.value = float(value)

    def _factor(self):
        return np.random.uniform(max(0, 1 - self.value), 1 + self.value)

    def __call__(self, img):
        return _ret(_arr(img).astype(np.float32) * self._factor(), img)


class ContrastTransform(BrightnessTransform):
    def __call__(self, img):
        arr = _arr(img).astype(np.float32)
        f = self._factor()
        return _ret(arr.mean() + f * (arr - arr.mean()), img)


class SaturationTransform(BrightnessTransform):
    def __call__(self, img):
        arr = _arr(img).astype(np.float32)
        chw, hwc = _hwc_view(arr)
        gray = Grayscale(hwc.shape[-1] if hwc.ndim == 3 else 1)
        g = _arr(gray(_back(hwc, False)))
        f = self._factor()
        out = g + f * (hwc - g)
        return _ret(_back(out, chw), img)


class HueTransform:
    """Hue shift by a uniform delta in [-value, value] (value <= 0.5),
    via RGB->HSV->RGB on floats."""

    def __init__(self, value):
        self.value = float(value)

    def __call__(self, img):
        delta = np.random.uniform(-self.value, self.value)
        arr = _arr(img).astype(np.float32)
        chw, hwc = _hwc_view(arr)
        return _ret(_back(_hue_shift(hwc, delta), chw), img)


def _hue_shift(hwc, delta):
    """Hue rotation by ``delta`` (in turns) via vectorized RGB→HSV→RGB
    on an HWC float array; preserves the input's value scale."""
    scale = 255.0 if hwc.max() > 1.5 else 1.0
    x = hwc / scale
    mx, mn = x[..., :3].max(-1), x[..., :3].min(-1)
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    c = mx - mn
    m = c > 1e-8
    rc = np.where(m, (mx - r) / np.where(m, c, 1), 0)
    gc = np.where(m, (mx - g) / np.where(m, c, 1), 0)
    bc = np.where(m, (mx - b) / np.where(m, c, 1), 0)
    hue = np.where(mx == r, bc - gc,
                   np.where(mx == g, 2 + rc - bc, 4 + gc - rc)) / 6.0
    hue = (hue + delta) % 1.0
    i = np.floor(hue * 6).astype(np.int64) % 6
    f = hue * 6 - np.floor(hue * 6)
    p, q, t = mn, mx - c * f, mx - c * (1 - f)
    rgb = np.stack([
        np.select([i == k for k in range(6)],
                  [mx, q, p, p, t, mx]),
        np.select([i == k for k in range(6)],
                  [t, mx, mx, q, p, p]),
        np.select([i == k for k in range(6)],
                  [p, p, t, mx, mx, q])], axis=-1)
    return rgb * scale


class ColorJitter:
    """Random brightness/contrast/saturation/hue in random order
    (reference: transforms.ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.ts = []
        if brightness:
            self.ts.append(BrightnessTransform(brightness))
        if contrast:
            self.ts.append(ContrastTransform(contrast))
        if saturation:
            self.ts.append(SaturationTransform(saturation))
        if hue:
            self.ts.append(HueTransform(hue))

    def __call__(self, img):
        for idx in np.random.permutation(len(self.ts)):
            img = self.ts[idx](img)
        return img


class RandomErasing:
    """Random rectangle erase (reference: transforms.RandomErasing)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0):
        self.prob, self.scale, self.ratio, self.value = \
            prob, scale, ratio, value

    def __call__(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = _arr(img).copy()
        chw, hwc = _hwc_view(arr)
        h, w = hwc.shape[:2]
        for _ in range(10):
            target = h * w * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh)
                j = np.random.randint(0, w - ew)
                hwc[i:i + eh, j:j + ew] = self.value
                break
        return _ret(_back(hwc, chw), img)


__all__ += ["Pad", "RandomRotation", "RandomResizedCrop", "Grayscale",
            "BrightnessTransform", "ContrastTransform",
            "SaturationTransform", "HueTransform", "ColorJitter",
            "RandomErasing", "resize"]


class RandomVerticalFlip:
    """(reference: transforms.RandomVerticalFlip — verify)."""

    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        return vflip(img) if np.random.rand() < self.prob else img


# ---------------------------------------------------------------------------
# functional API (reference: python/paddle/vision/transforms/functional.py
# — verify): deterministic single-image versions of the classes above
# ---------------------------------------------------------------------------

def hflip(img):
    """Flip horizontally (W axis; HWC or CHW)."""
    arr = _arr(img)
    chw, hwc = _hwc_view(arr)
    return _ret(_back(hwc[:, ::-1], chw), img)


def vflip(img):
    """Flip vertically (H axis)."""
    arr = _arr(img)
    chw, hwc = _hwc_view(arr)
    return _ret(_back(hwc[::-1], chw), img)


def crop(img, top, left, height, width):
    arr = _arr(img)
    chw, hwc = _hwc_view(arr)
    return _ret(_back(hwc[top:top + height, left:left + width], chw), img)


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def pad(img, padding, fill=0, padding_mode="constant"):
    return Pad(padding, fill=fill, padding_mode=padding_mode)(img)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate counter-clockwise by ``angle`` degrees (nearest sampling,
    same canvas — reference: F.rotate; expand is not supported)."""
    if expand:
        raise NotImplementedError("rotate(expand=True) is unsupported")
    if interpolation != "nearest":
        raise NotImplementedError(
            f"rotate(interpolation={interpolation!r}): only 'nearest' "
            "sampling is implemented")
    arr = _arr(img).astype(np.float32)
    chw, hwc = _hwc_view(arr)
    return _ret(_back(_rotate_nearest(hwc, angle, fill, center), chw),
                img)


def to_grayscale(img, num_output_channels=1):
    return Grayscale(num_output_channels)(img)


def adjust_brightness(img, brightness_factor):
    return _ret(_arr(img).astype(np.float32) * float(brightness_factor),
                img)


def adjust_contrast(img, contrast_factor):
    arr = _arr(img).astype(np.float32)
    f = float(contrast_factor)
    return _ret(arr.mean() + f * (arr - arr.mean()), img)


def adjust_hue(img, hue_factor):
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError(f"hue_factor {hue_factor} not in [-0.5, 0.5]")
    arr = _arr(img).astype(np.float32)
    chw, hwc = _hwc_view(arr)
    if hwc.ndim != 3 or hwc.shape[-1] != 3:
        raise ValueError(
            f"adjust_hue needs a 3-channel image, got shape {arr.shape}")
    return _ret(_back(_hue_shift(hwc, float(hue_factor)), chw), img)


__all__ += ["RandomVerticalFlip", "hflip", "vflip", "crop", "center_crop",
            "pad", "rotate", "to_grayscale", "adjust_brightness",
            "adjust_contrast", "adjust_hue"]


def _warp_inverse_nearest(hwc, inv, fill=0):
    """Warp by a 3x3 inverse homography (dst (x,y,1) -> src), nearest
    sampling, same canvas — the shared engine for RandomAffine /
    RandomPerspective (reference: transforms.{RandomAffine,
    RandomPerspective} — verify)."""
    h, w = hwc.shape[:2]
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float64)
    den = inv[2, 0] * xx + inv[2, 1] * yy + inv[2, 2]
    den = np.where(np.abs(den) < 1e-12, 1e-12, den)
    sx = (inv[0, 0] * xx + inv[0, 1] * yy + inv[0, 2]) / den
    sy = (inv[1, 0] * xx + inv[1, 1] * yy + inv[1, 2]) / den
    sxi = np.round(sx).astype(np.int64)
    syi = np.round(sy).astype(np.int64)
    valid = (syi >= 0) & (syi < h) & (sxi >= 0) & (sxi < w)
    out = hwc[syi.clip(0, h - 1), sxi.clip(0, w - 1)].copy()
    out[~valid] = fill
    return out


class RandomAffine:
    """Random rotation + translation + scale + shear about the image
    center (reference: transforms.RandomAffine — verify; torchvision
    parameter semantics: translate as width/height fractions, shear in
    degrees). Nearest sampling, matching this module's RandomRotation."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None):
        if interpolation != "nearest":
            raise NotImplementedError(
                "RandomAffine: only 'nearest' sampling is implemented")
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)
        self.translate = translate
        self.scale_range = scale
        if shear is None:
            self.shear = None
        elif np.isscalar(shear):
            self.shear = (-shear, shear, 0.0, 0.0)
        elif len(shear) == 2:
            self.shear = (shear[0], shear[1], 0.0, 0.0)
        else:
            self.shear = tuple(shear)
        self.fill = fill
        self.center = center

    def _matrix(self, h, w):
        angle = np.deg2rad(np.random.uniform(*self.degrees))
        tx = ty = 0.0
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0],
                                   self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1],
                                   self.translate[1]) * h
        s = np.random.uniform(*self.scale_range) \
            if self.scale_range is not None else 1.0
        shx = shy = 0.0
        if self.shear is not None:
            shx = np.deg2rad(np.random.uniform(self.shear[0],
                                               self.shear[1]))
            shy = np.deg2rad(np.random.uniform(self.shear[2],
                                               self.shear[3]))
        cx, cy = ((w - 1) / 2.0, (h - 1) / 2.0) if self.center is None \
            else self.center
        cos, sin = np.cos(angle), np.sin(angle)
        # y-down pixel grid: visually-CCW positive angles (matching
        # this module's RandomRotation: rotate(90) == np.rot90(img, 1))
        rot = np.array([[cos, sin, 0], [-sin, cos, 0], [0, 0, 1]])
        sh = np.array([[1, np.tan(shx), 0], [np.tan(shy), 1, 0],
                       [0, 0, 1]])
        sc = np.diag([s, s, 1.0])
        t_c = np.array([[1, 0, cx], [0, 1, cy], [0, 0, 1]])
        t_ci = np.array([[1, 0, -cx], [0, 1, -cy], [0, 0, 1]])
        t_tr = np.array([[1, 0, tx], [0, 1, ty], [0, 0, 1]])
        return t_tr @ t_c @ rot @ sh @ sc @ t_ci

    def __call__(self, img):
        arr = _arr(img).astype(np.float32)
        chw, hwc = _hwc_view(arr)
        h, w = hwc.shape[:2]
        inv = np.linalg.inv(self._matrix(h, w))
        return _ret(_back(_warp_inverse_nearest(hwc, inv, self.fill),
                          chw), img)


class RandomPerspective:
    """Random four-corner perspective distortion (reference:
    transforms.RandomPerspective — verify): each output corner pulls
    inward by up to ``distortion_scale * side/2``; applied with
    probability ``prob``. Nearest sampling."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0):
        if interpolation != "nearest":
            raise NotImplementedError(
                "RandomPerspective: only 'nearest' is implemented")
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.fill = fill

    @staticmethod
    def _homography(src, dst):
        """3x3 H with H @ [x_src, y_src, 1] ~ [x_dst, y_dst, 1] (DLT)."""
        a, b = [], []
        for (x, y), (u, v) in zip(src, dst):
            a.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
            a.append([0, 0, 0, x, y, 1, -v * x, -v * y])
            b += [u, v]
        h8 = np.linalg.solve(np.asarray(a, np.float64),
                             np.asarray(b, np.float64))
        return np.append(h8, 1.0).reshape(3, 3)

    def __call__(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = _arr(img).astype(np.float32)
        chw, hwc = _hwc_view(arr)
        h, w = hwc.shape[:2]
        dx, dy = self.distortion_scale * w / 2, \
            self.distortion_scale * h / 2
        corners = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        signs = [(1, 1), (-1, 1), (-1, -1), (1, -1)]
        warped = [(x + sx * np.random.uniform(0, dx),
                   y + sy * np.random.uniform(0, dy))
                  for (x, y), (sx, sy) in zip(corners, signs)]
        # output corner (dst) pulls its content from the perturbed
        # source corner: inverse map dst -> src
        inv = self._homography(corners, warped)
        return _ret(_back(_warp_inverse_nearest(hwc, inv, self.fill),
                          chw), img)


__all__ += ["RandomAffine", "RandomPerspective"]


# --------------------------------------------------------------------------
# AutoAugment (reference: transforms.AutoAugment, ImageNet policy —
# verify magnitude tables). Operates on HWC uint8-range float arrays;
# geometric ops ride _warp_inverse_nearest, pixel ops are numpy.
# --------------------------------------------------------------------------

def _aa_affine(hwc, mat, fill):
    return _warp_inverse_nearest(hwc, np.linalg.inv(mat), fill)


def _aa_blend(a, b, alpha):
    return a + (b - a) * alpha


def _aa_apply(name, hwc, mag, fill=128):
    h, w = hwc.shape[:2]
    cx, cy = (w - 1) / 2.0, (h - 1) / 2.0
    t_c = np.array([[1, 0, cx], [0, 1, cy], [0, 0, 1.]])
    t_ci = np.array([[1, 0, -cx], [0, 1, -cy], [0, 0, 1.]])
    x = hwc.astype(np.float32)
    if name == "shearX":
        m = np.array([[1, mag, 0], [0, 1, 0], [0, 0, 1.]])
        return _aa_affine(x, t_c @ m @ t_ci, fill)
    if name == "shearY":
        m = np.array([[1, 0, 0], [mag, 1, 0], [0, 0, 1.]])
        return _aa_affine(x, t_c @ m @ t_ci, fill)
    if name == "translateX":
        m = np.array([[1, 0, mag * w], [0, 1, 0], [0, 0, 1.]])
        return _aa_affine(x, m, fill)
    if name == "translateY":
        m = np.array([[1, 0, 0], [0, 1, mag * h], [0, 0, 1.]])
        return _aa_affine(x, m, fill)
    if name == "rotate":
        rad = np.deg2rad(mag)
        cos, sin = np.cos(rad), np.sin(rad)
        m = np.array([[cos, sin, 0], [-sin, cos, 0], [0, 0, 1.]])
        return _aa_affine(x, t_c @ m @ t_ci, fill)
    if name == "invert":
        return 255.0 - x
    if name == "solarize":
        return np.where(x >= mag, 255.0 - x, x)
    if name == "posterize":
        bits = int(mag)
        shift = 8 - bits
        q = (x.astype(np.uint8) >> shift) << shift
        return q.astype(np.float32)
    if name == "autocontrast":
        lo = x.min(axis=(0, 1), keepdims=True)
        hi = x.max(axis=(0, 1), keepdims=True)
        scale = 255.0 / np.maximum(hi - lo, 1e-6)
        return np.where(hi > lo, (x - lo) * scale, x)
    if name == "equalize":
        out = np.empty_like(x)
        for c in range(x.shape[2]):
            ch = x[:, :, c].astype(np.uint8)
            hist = np.bincount(ch.ravel(), minlength=256)
            nz = hist[hist > 0]
            if len(nz) <= 1:
                out[:, :, c] = ch
                continue
            step = (hist.sum() - nz[-1]) // 255
            if step == 0:
                out[:, :, c] = ch
                continue
            lut = (np.cumsum(hist) - hist) // step
            out[:, :, c] = np.clip(lut[ch], 0, 255)
        return out.astype(np.float32)
    if name == "contrast":
        mean = x.mean()
        return _aa_blend(np.full_like(x, mean), x, mag)
    if name == "color":
        gray = x @ np.array([0.299, 0.587, 0.114], np.float32) \
            if x.shape[2] == 3 else x.mean(axis=2)
        return _aa_blend(gray[..., None].repeat(x.shape[2], 2), x, mag)
    if name == "brightness":
        return _aa_blend(np.zeros_like(x), x, mag)
    if name == "sharpness":
        k = np.array([[1, 1, 1], [1, 5, 1], [1, 1, 1]], np.float32) / 13
        pad = np.pad(x, ((1, 1), (1, 1), (0, 0)), mode="edge")
        sm = sum(k[i, j] * pad[i:i + x.shape[0], j:j + x.shape[1]]
                 for i in range(3) for j in range(3))
        out = _aa_blend(sm, x, mag)
        out[0], out[-1] = x[0], x[-1]       # PIL keeps the border
        out[:, 0], out[:, -1] = x[:, 0], x[:, -1]
        return out
    raise ValueError(f"unknown AutoAugment op {name!r}")


# (op, prob, magnitude-bin 0..9) pairs — the published ImageNet policy
_IMAGENET_POLICY = [
    (("posterize", 0.4, 8), ("rotate", 0.6, 9)),
    (("solarize", 0.6, 5), ("autocontrast", 0.6, 5)),
    (("equalize", 0.8, 8), ("equalize", 0.6, 3)),
    (("posterize", 0.6, 7), ("posterize", 0.6, 6)),
    (("equalize", 0.4, 7), ("solarize", 0.2, 4)),
    (("equalize", 0.4, 4), ("rotate", 0.8, 8)),
    (("solarize", 0.6, 3), ("equalize", 0.6, 7)),
    (("posterize", 0.8, 5), ("equalize", 1.0, 2)),
    (("rotate", 0.2, 3), ("solarize", 0.6, 8)),
    (("equalize", 0.6, 8), ("posterize", 0.4, 6)),
    (("rotate", 0.8, 8), ("color", 0.4, 0)),
    (("rotate", 0.4, 9), ("equalize", 0.6, 2)),
    (("equalize", 0.0, 7), ("equalize", 0.8, 8)),
    (("invert", 0.6, 4), ("equalize", 1.0, 8)),
    (("color", 0.6, 4), ("contrast", 1.0, 8)),
    (("rotate", 0.8, 8), ("color", 1.0, 2)),
    (("color", 0.8, 8), ("solarize", 0.8, 7)),
    (("sharpness", 0.4, 7), ("invert", 0.6, 8)),
    (("shearX", 0.6, 5), ("equalize", 1.0, 9)),
    (("color", 0.4, 0), ("equalize", 0.6, 3)),
    (("equalize", 0.4, 7), ("solarize", 0.2, 4)),
    (("solarize", 0.6, 5), ("autocontrast", 0.6, 5)),
    (("invert", 0.6, 4), ("equalize", 1.0, 8)),
    (("color", 0.6, 4), ("contrast", 1.0, 8)),
    (("equalize", 0.8, 8), ("equalize", 0.6, 3)),
]

_AA_RANGES = {
    "shearX": np.linspace(0, 0.3, 10),
    "shearY": np.linspace(0, 0.3, 10),
    "translateX": np.linspace(0, 150.0 / 331.0, 10),
    "translateY": np.linspace(0, 150.0 / 331.0, 10),
    "rotate": np.linspace(0, 30, 10),
    "solarize": np.linspace(256, 0, 10),
    "posterize": np.round(np.linspace(8, 4, 10)),
    # enhancement ops: the table stores the DEVIATION from identity;
    # __call__ sign-randomizes it and applies factor 1.0 + signed_mag
    # (published policy / torchvision behavior — so color/contrast/
    # brightness/sharpness can also darken/desaturate/blur)
    "contrast": np.linspace(0, 0.9, 10),
    "color": np.linspace(0, 0.9, 10),
    "brightness": np.linspace(0, 0.9, 10),
    "sharpness": np.linspace(0, 0.9, 10),
    "autocontrast": np.zeros(10),
    "equalize": np.zeros(10),
    "invert": np.zeros(10),
}
_AA_SIGNED = {"shearX", "shearY", "translateX", "translateY", "rotate",
              "color", "contrast", "brightness", "sharpness"}
# enhancement ops whose signed magnitude is a deviation from the
# identity factor 1.0
_AA_ENHANCE = {"color", "contrast", "brightness", "sharpness"}


class AutoAugment:
    """AutoAugment with the published ImageNet policy (reference:
    transforms.AutoAugment — verify): per call, one random sub-policy's
    two (op, prob, magnitude) steps are applied. Magnitudes of the
    geometric AND enhancement ops are sign-randomized as in the paper;
    enhancement factors apply as 1.0 +/- mag, so color/contrast/
    brightness/sharpness can also desaturate/darken/blur."""

    def __init__(self, policy="imagenet", fill=128):
        if policy != "imagenet":
            raise NotImplementedError(
                f"AutoAugment(policy={policy!r}): only 'imagenet'")
        self.fill = fill

    def __call__(self, img):
        arr = _arr(img).astype(np.float32)
        chw, hwc = _hwc_view(arr)
        sub = _IMAGENET_POLICY[np.random.randint(len(_IMAGENET_POLICY))]
        for op, prob, bin_ in sub:
            if np.random.rand() > prob:
                continue
            mag = float(_AA_RANGES[op][bin_])
            if op in _AA_SIGNED and np.random.rand() < 0.5:
                mag = -mag
            if op in _AA_ENHANCE:
                mag = 1.0 + mag
            hwc = _aa_apply(op, hwc, mag, self.fill)
        out = np.clip(hwc, 0, 255)
        return _ret(_back(out, chw), img)


__all__ += ["AutoAugment"]
