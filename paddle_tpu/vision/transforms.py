"""Vision transforms on numpy/Tensor (reference:
python/paddle/vision/transforms/ — verify)."""
from __future__ import annotations

import numpy as np

from ..tensor import Tensor, to_tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "Transpose", "normalize",
           "to_tensor_fn"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, dtype=np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return to_tensor(arr)


to_tensor_fn = ToTensor


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img._value if isinstance(img, Tensor) else img,
                         dtype=np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        arr = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return to_tensor(arr) if isinstance(img, Tensor) else arr


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)

    def __call__(self, img):
        import jax
        import jax.numpy as jnp
        arr = np.asarray(img._value if isinstance(img, Tensor) else img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        if chw:
            out_shape = (arr.shape[0],) + tuple(self.size)
        elif arr.ndim == 3:
            out_shape = tuple(self.size) + (arr.shape[2],)
        else:
            out_shape = tuple(self.size)
        out = jax.image.resize(jnp.asarray(arr, jnp.float32), out_shape,
                               "linear")
        return to_tensor(out) if isinstance(img, Tensor) else np.asarray(out)


class CenterCrop:
    def __init__(self, size):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)

    def __call__(self, img):
        arr = np.asarray(img._value if isinstance(img, Tensor) else img)
        h_axis = 1 if (arr.ndim == 3 and arr.shape[0] in (1, 3)) else 0
        h, w = arr.shape[h_axis], arr.shape[h_axis + 1]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        sl = [slice(None)] * arr.ndim
        sl[h_axis] = slice(i, i + th)
        sl[h_axis + 1] = slice(j, j + tw)
        out = arr[tuple(sl)]
        return to_tensor(out) if isinstance(img, Tensor) else out


class RandomCrop(CenterCrop):
    def __call__(self, img):
        arr = np.asarray(img._value if isinstance(img, Tensor) else img)
        h_axis = 1 if (arr.ndim == 3 and arr.shape[0] in (1, 3)) else 0
        h, w = arr.shape[h_axis], arr.shape[h_axis + 1]
        th, tw = self.size
        i = np.random.randint(0, max(h - th, 0) + 1)
        j = np.random.randint(0, max(w - tw, 0) + 1)
        sl = [slice(None)] * arr.ndim
        sl[h_axis] = slice(i, i + th)
        sl[h_axis + 1] = slice(j, j + tw)
        out = arr[tuple(sl)]
        return to_tensor(out) if isinstance(img, Tensor) else out


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            arr = np.asarray(img._value if isinstance(img, Tensor) else img)
            out = arr[..., ::-1].copy()
            return to_tensor(out) if isinstance(img, Tensor) else out
        return img


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        arr = np.asarray(img._value if isinstance(img, Tensor) else img)
        out = arr.transpose(self.order)
        return to_tensor(out) if isinstance(img, Tensor) else out
