"""Vision datasets (reference: python/paddle/vision/datasets/ — verify).
No network in this environment: the standard named datasets raise with a
download hint unless data files exist locally; FakeData provides the
synthetic path used by tests/benchmarks."""
from __future__ import annotations

import os

import numpy as np

from ..io import Dataset

__all__ = ["FakeData", "MNIST", "Cifar10", "Cifar100", "DatasetFolder",
           "ImageFolder"]


class FakeData(Dataset):
    """Synthetic images+labels (deterministic per index)."""

    def __init__(self, size=1000, image_shape=(3, 224, 224), num_classes=10,
                 transform=None):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        rng = np.random.RandomState(idx)
        img = rng.rand(*self.image_shape).astype(np.float32)
        label = np.int32(rng.randint(self.num_classes))
        if self.transform:
            img = self.transform(img)
        return img, label


class _FileBacked(Dataset):
    URL_HINT = ""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        if data_file is None or not os.path.exists(data_file):
            raise RuntimeError(
                f"{type(self).__name__}: dataset file not found "
                f"(no network egress in this environment; place the file "
                f"locally and pass data_file=). {self.URL_HINT}")
        self.data_file = data_file
        self._load()

    def _load(self):
        raise NotImplementedError


class MNIST(_FileBacked):
    URL_HINT = "expects the idx-format images/labels gz pair"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        import gzip
        import struct
        for p in (image_path, label_path):
            if p is None or not os.path.exists(p):
                raise RuntimeError(
                    "MNIST: pass local image_path/label_path (no egress)")
        with gzip.open(image_path, "rb") as f:
            _, n, rows, cols = struct.unpack(">IIII", f.read(16))
            self.images = np.frombuffer(f.read(), np.uint8).reshape(
                n, rows, cols)
        with gzip.open(label_path, "rb") as f:
            struct.unpack(">II", f.read(8))
            self.labels = np.frombuffer(f.read(), np.uint8)
        self.transform = transform

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None] / 255.0
        if self.transform:
            img = self.transform(img)
        return img, np.int32(self.labels[idx])


class Cifar10(_FileBacked):
    URL_HINT = "expects the python-pickle cifar batches tar"

    def _load(self):
        import pickle
        import tarfile
        datas, labels = [], []
        with tarfile.open(self.data_file) as tf:
            names = [m for m in tf.getmembers()
                     if ("data_batch" in m.name if self.mode == "train"
                         else "test_batch" in m.name)]
            for m in names:
                d = pickle.load(tf.extractfile(m), encoding="bytes")
                datas.append(d[b"data"])
                labels.extend(d[b"labels"])
        self.data = np.concatenate(datas).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int32)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.data[idx].astype(np.float32) / 255.0
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]


class Cifar100(Cifar10):
    pass


class DatasetFolder(Dataset):
    """ImageNet-style folder-per-class dataset; requires an image decoder
    backend (PIL/cv2) present locally."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        exts = extensions or (".npy",)
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(exts):
                    self.samples.append((os.path.join(cdir, fn),
                                         self.class_to_idx[c]))
        self.loader = loader or (lambda p: np.load(p))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform:
            img = self.transform(img)
        return img, np.int32(target)


class ImageFolder(DatasetFolder):
    def __getitem__(self, idx):
        path, _ = self.samples[idx]
        img = self.loader(path)
        if self.transform:
            img = self.transform(img)
        return (img,)


class Flowers(Dataset):
    """Oxford 102 Flowers (reference: python/paddle/vision/datasets/
    flowers.py — verify). Three local files (no egress): the image
    tarball (102flowers.tgz: jpg/image_*.jpg), imagelabels.mat
    (1-based class per image) and setid.mat (trnid/valid/tstid splits).
    Images decode lazily from the tarball on __getitem__; ``backend``
    'pil' returns PIL images, 'cv2'/None HWC uint8 arrays."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True,
                 backend=None):
        import scipy.io as sio
        for name, p in (("data_file", data_file),
                        ("label_file", label_file),
                        ("setid_file", setid_file)):
            if p is None or not os.path.exists(p):
                raise RuntimeError(
                    f"Flowers: {name} not found (no network egress; "
                    "place 102flowers.tgz / imagelabels.mat / "
                    f"setid.mat locally and pass {name}=)")
        self.transform = transform
        self.backend = backend
        labels = sio.loadmat(label_file)["labels"].ravel()
        # the reference loader deliberately SWAPS the .mat splits:
        # 'train' uses the large tstid set (6149 images), 'test' the
        # small trnid set (1020) — python/paddle/vision/datasets/
        # flowers.py
        key = {"train": "tstid", "valid": "validid",
               "test": "trnid"}.get(mode, "tstid")
        setid = sio.loadmat(setid_file)
        if key not in setid and key == "validid":
            key = "valid"          # both spellings appear in the wild
        self.indexes = setid[key].ravel()
        self.labels = labels
        self.data_file = data_file
        self._tar = None
        self._names = None

    def _open(self):
        import tarfile
        if self._tar is None:
            self._tar = tarfile.open(self.data_file, "r:*")
            self._names = {os.path.basename(n): n
                           for n in self._tar.getnames()
                           if n.endswith(".jpg")}
        return self._tar

    def __len__(self):
        return len(self.indexes)

    def __getitem__(self, idx):
        from PIL import Image
        import io as _io
        n = int(self.indexes[idx])          # 1-based image number
        tf = self._open()
        member = self._names[f"image_{n:05d}.jpg"]
        img = Image.open(_io.BytesIO(tf.extractfile(member).read()))
        img = img.convert("RGB")
        if self.backend != "pil":
            img = np.asarray(img, np.uint8)
        if self.transform is not None:
            img = self.transform(img)
        # imagelabels.mat is 1-based; a 102-class head needs 0..101
        label = np.int64(self.labels[n - 1] - 1)
        return img, label

    def __getstate__(self):
        # DataLoader workers: the open tar handle cannot cross a fork
        s = dict(self.__dict__)
        s["_tar"] = None
        s["_names"] = None
        return s


__all__ += ["Flowers"]
