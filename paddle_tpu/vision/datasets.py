"""Vision datasets (reference: python/paddle/vision/datasets/ — verify).
No network in this environment: the standard named datasets raise with a
download hint unless data files exist locally; FakeData provides the
synthetic path used by tests/benchmarks."""
from __future__ import annotations

import os

import numpy as np

from ..io import Dataset

__all__ = ["FakeData", "MNIST", "Cifar10", "Cifar100", "DatasetFolder",
           "ImageFolder"]


class FakeData(Dataset):
    """Synthetic images+labels (deterministic per index)."""

    def __init__(self, size=1000, image_shape=(3, 224, 224), num_classes=10,
                 transform=None):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        rng = np.random.RandomState(idx)
        img = rng.rand(*self.image_shape).astype(np.float32)
        label = np.int32(rng.randint(self.num_classes))
        if self.transform:
            img = self.transform(img)
        return img, label


class _FileBacked(Dataset):
    URL_HINT = ""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        if data_file is None or not os.path.exists(data_file):
            raise RuntimeError(
                f"{type(self).__name__}: dataset file not found "
                f"(no network egress in this environment; place the file "
                f"locally and pass data_file=). {self.URL_HINT}")
        self.data_file = data_file
        self._load()

    def _load(self):
        raise NotImplementedError


class MNIST(_FileBacked):
    URL_HINT = "expects the idx-format images/labels gz pair"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        import gzip
        import struct
        for p in (image_path, label_path):
            if p is None or not os.path.exists(p):
                raise RuntimeError(
                    "MNIST: pass local image_path/label_path (no egress)")
        with gzip.open(image_path, "rb") as f:
            _, n, rows, cols = struct.unpack(">IIII", f.read(16))
            self.images = np.frombuffer(f.read(), np.uint8).reshape(
                n, rows, cols)
        with gzip.open(label_path, "rb") as f:
            struct.unpack(">II", f.read(8))
            self.labels = np.frombuffer(f.read(), np.uint8)
        self.transform = transform

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None] / 255.0
        if self.transform:
            img = self.transform(img)
        return img, np.int32(self.labels[idx])


class Cifar10(_FileBacked):
    URL_HINT = "expects the python-pickle cifar batches tar"

    def _load(self):
        import pickle
        import tarfile
        datas, labels = [], []
        with tarfile.open(self.data_file) as tf:
            names = [m for m in tf.getmembers()
                     if ("data_batch" in m.name if self.mode == "train"
                         else "test_batch" in m.name)]
            for m in names:
                d = pickle.load(tf.extractfile(m), encoding="bytes")
                datas.append(d[b"data"])
                labels.extend(d[b"labels"])
        self.data = np.concatenate(datas).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int32)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.data[idx].astype(np.float32) / 255.0
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]


class Cifar100(Cifar10):
    pass


class DatasetFolder(Dataset):
    """ImageNet-style folder-per-class dataset; requires an image decoder
    backend (PIL/cv2) present locally."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        exts = extensions or (".npy",)
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(exts):
                    self.samples.append((os.path.join(cdir, fn),
                                         self.class_to_idx[c]))
        self.loader = loader or (lambda p: np.load(p))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform:
            img = self.transform(img)
        return img, np.int32(target)


class ImageFolder(DatasetFolder):
    def __getitem__(self, idx):
        path, _ = self.samples[idx]
        img = self.loader(path)
        if self.transform:
            img = self.transform(img)
        return (img,)
