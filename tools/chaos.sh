#!/usr/bin/env bash
# Seeded worker-kill chaos soak for the serving fleet (CPU lane).
#
# Drives one traffic run on a paged 2-prefill/2-decode Fleet over the
# REAL localhost-TCP SocketTransport with ~1% wire faults armed
# (transport.partial_write/corrupt/disconnect), kills K decode workers
# at seeded ticks (scaling a fresh worker in after each kill), and
# asserts the failure-domain invariants:
#   - every request completed OR ended in an explicit RequestFailure
#   - completed greedy rows bit-identical to generate()
#   - zero block leaks on every surviving arena (prefill AND decode)
#
# Usage: tools/chaos.sh [SEED] [KILLS] [REQUESTS]
#   SEED     fault/kill schedule seed        (default 0)
#   KILLS    decode workers to kill          (default 2)
#   REQUESTS traffic size                    (default 12)
#
# The same SEED replays the identical kill+fault schedule bit-for-bit.
# Exits non-zero on any invariant violation.
set -euo pipefail
cd "$(dirname "$0")/.."

SEED="${1:-0}"
KILLS="${2:-2}"
REQUESTS="${3:-12}"

JAX_PLATFORMS=cpu python - "$SEED" "$KILLS" "$REQUESTS" <<'PY'
import json
import sys

import jax
# the documented jaxlib landmine: a stale persistent compile cache can
# corrupt the heap when additional paged backends compile in-process
# (ROADMAP env note); the soak compiles one per kill, so stay cold
jax.config.update("jax_enable_compilation_cache", False)

from paddle_tpu.serving.microbench import run_fleet_kill_soak

seed, kills, requests = (int(a) for a in sys.argv[1:4])
out = run_fleet_kill_soak(seed=seed, kills=kills, requests=requests)
print("CHAOS_JSON " + json.dumps(out))
assert out["soak_completed"] + out["soak_failed"] == out["soak_requests"]
print(f"chaos soak OK: seed={seed} kills={out['soak_kills']} "
      f"completed={out['soak_completed']} failed={out['soak_failed']} "
      f"redrives={out['soak_redrives']} leaks={out['soak_leaks']}")
PY
