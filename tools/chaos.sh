#!/usr/bin/env bash
# Seeded worker-kill chaos soak for the serving fleet (CPU lane).
#
# Drives one traffic run on a paged 2-prefill/2-decode Fleet over the
# REAL localhost-TCP SocketTransport with ~1% wire faults armed
# (transport.partial_write/corrupt/disconnect), kills K decode workers
# at seeded ticks (scaling a fresh worker in after each kill), and
# asserts the failure-domain invariants:
#   - every request completed OR ended in an explicit RequestFailure
#   - completed greedy rows bit-identical to generate()
#   - zero block leaks on every surviving arena (prefill AND decode)
#
# Usage: tools/chaos.sh [SEED] [KILLS] [REQUESTS]
#   SEED     fault/kill schedule seed        (default 0)
#   KILLS    decode workers to kill          (default 2)
#   REQUESTS traffic size                    (default 12)
#
# The same SEED replays the identical kill+fault schedule bit-for-bit.
# Exits non-zero on any invariant violation.
set -euo pipefail
cd "$(dirname "$0")/.."

SEED="${1:-0}"
KILLS="${2:-2}"
REQUESTS="${3:-12}"

JAX_PLATFORMS=cpu python - "$SEED" "$KILLS" "$REQUESTS" <<'PY'
import json
import sys

import jax
# the documented jaxlib landmine: a stale persistent compile cache can
# corrupt the heap when additional paged backends compile in-process
# (ROADMAP env note); the soak compiles one per kill, so stay cold
jax.config.update("jax_enable_compilation_cache", False)

from paddle_tpu.serving.microbench import run_fleet_kill_soak

seed, kills, requests = (int(a) for a in sys.argv[1:4])
out = run_fleet_kill_soak(seed=seed, kills=kills, requests=requests)
print("CHAOS_JSON " + json.dumps(out))
assert out["soak_completed"] + out["soak_failed"] == out["soak_requests"]
print(f"chaos soak OK: seed={seed} kills={out['soak_kills']} "
      f"completed={out['soak_completed']} failed={out['soak_failed']} "
      f"redrives={out['soak_redrives']} leaks={out['soak_leaks']}")
PY

# ---------------------------------------------------------------------------
# Whole-fleet crash soak: a REAL SIGKILL mid-burst, then recovery in a
# fresh process from the durability directory alone.
#
# Phase 1 drives a durable paged fleet (write-ahead journal armed, one
# coordinated checkpoint mid-traffic) and SIGKILLs ITSELF at a seeded
# tick with streams queued, mid-chunked-prefill, shipped-in-transit and
# adopted-and-decoding. Phase 2 is a fresh interpreter: Fleet.recover
# from the surviving directory, run to idle, and assert
#   - every journaled request completed OR ended in an explicit
#     RequestFailure (none vanished in the crash)
#   - every completed greedy row bit-identical to generate(), every
#     seeded-sampled row bit-identical to generate(do_sample=True,...)
#     — the prompts/kw come from the durable records themselves
#   - exactly one terminal per request across pre/post-crash state
#   - zero block leaks, decode compile counts still 1
# ---------------------------------------------------------------------------

DUR_DIR="$(mktemp -d /tmp/pt-chaos-recover.XXXXXX)"
trap 'rm -rf "$DUR_DIR"' EXIT

echo "whole-fleet crash soak: durability dir $DUR_DIR"
set +e
JAX_PLATFORMS=cpu PT_CHAOS_DUR_DIR="$DUR_DIR" \
    python - "$SEED" "$REQUESTS" <<'PY'
import os
import signal
import sys

import numpy as np
import jax
jax.config.update("jax_enable_compilation_cache", False)

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
from paddle_tpu.serving import (ContinuousBatchingEngine, DecodeWorker,
                                Fleet, PrefillPagedEngine,
                                PrefillWorker)

seed, requests = (int(a) for a in sys.argv[1:3])
paddle.seed(0)
cfg = llama_tiny_config(tensor_parallel=False)
model = LlamaForCausalLM(cfg)
kw = dict(num_slots=2, max_len=64, decode_block=4, block_size=8,
          prefill_chunk=8)
fleet = Fleet(
    [PrefillWorker(PrefillPagedEngine(model, **kw)) for _ in range(2)],
    [DecodeWorker(ContinuousBatchingEngine(model, paged=True, **kw))
     for _ in range(2)],
    durability=os.environ["PT_CHAOS_DUR_DIR"])

rs = np.random.RandomState(seed)
lens = rs.randint(5, 18, size=requests)
prompts = [rs.randint(0, cfg.vocab_size, (int(L),)).astype(np.int32)
           for L in lens]
kill_tick = int(rs.randint(4, 8))
for i, p in enumerate(prompts[: requests // 2]):
    skw = {} if i % 3 else {"temperature": 0.9, "top_k": 40,
                            "seed": 11 + i}
    fleet.submit(p, max_new_tokens=10, **skw)
for _ in range(3):
    fleet.tick()
fleet.checkpoint()
for i, p in enumerate(prompts[requests // 2:], start=requests // 2):
    skw = {} if i % 3 else {"temperature": 0.9, "top_k": 40,
                            "seed": 11 + i}
    fleet.submit(p, max_new_tokens=10, **skw)
for t in range(kill_tick):
    fleet.tick()
print(f"phase 1: SIGKILL at tick {fleet._clock} "
      f"(kill_tick={kill_tick})", flush=True)
os.kill(os.getpid(), signal.SIGKILL)      # the crash is REAL
raise SystemExit("unreachable")
PY
rc=$?
set -e
if [ "$rc" -eq 0 ]; then
    echo "phase 1 exited cleanly — the SIGKILL never fired" >&2
    exit 1
fi
echo "phase 1 died rc=$rc (expected); recovering in a fresh process"

JAX_PLATFORMS=cpu PT_CHAOS_DUR_DIR="$DUR_DIR" python - <<'PY'
import os

import numpy as np
import jax
jax.config.update("jax_enable_compilation_cache", False)

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
from paddle_tpu.serving import (ContinuousBatchingEngine, DecodeWorker,
                                Fleet, PrefillPagedEngine,
                                PrefillWorker, RequestFailure)

paddle.seed(0)
cfg = llama_tiny_config(tensor_parallel=False)
model = LlamaForCausalLM(cfg)
kw = dict(num_slots=2, max_len=64, decode_block=4, block_size=8,
          prefill_chunk=8)


def make(role, name):
    if role == "prefill":
        return PrefillPagedEngine(model, **kw)
    return ContinuousBatchingEngine(model, paged=True, **kw)


fleet = Fleet.recover(os.environ["PT_CHAOS_DUR_DIR"],
                      engine_factory=make)
print(f"recovered: {fleet.last_recovery}")
fleet.run_until_idle(max_ticks=600)
res = fleet.results
assert fleet._requests, "no journaled requests survived the crash"
completed = failed = 0
for rid, rec in sorted(fleet._requests.items()):
    v = res.get(rid)
    assert v is not None, f"rid {rid} vanished in the crash"
    if isinstance(v, RequestFailure):
        failed += 1
        continue
    completed += 1
    rkw = dict(rec["kw"])
    mn = rkw.pop("max_new_tokens")
    if rkw.get("temperature", 0.0) > 0.0:
        ref = model.generate(paddle.to_tensor(
            np.asarray(rec["prompt"], np.int32)[None, :]),
            max_new_tokens=mn, do_sample=True, **rkw).numpy()[0]
    else:
        ref = model.generate(paddle.to_tensor(
            np.asarray(rec["prompt"], np.int32)[None, :]),
            max_new_tokens=mn).numpy()[0]
    assert np.array_equal(np.asarray(v), ref), \
        f"rid {rid} diverged through the crash"
    owners = sum(1 for w in fleet.prefill + fleet.decode
                 if rid in w.server.results) \
        + int(rid in fleet._local_results) + int(rid in fleet._failures)
    assert owners == 1, f"rid {rid}: {owners} terminals"
for w in fleet.prefill + fleet.decode:
    assert all(s is None for s in w.engine._slots), w.name
    if hasattr(w.engine, "manager"):
        assert not w.engine.manager._ref, f"block leak on {w.name}"
        w.engine.manager.assert_consistent()
for d in fleet.decode:
    assert d.engine.decode_compile_count() == 1, \
        "recovery recompiled the decode block"
print(f"whole-fleet crash soak OK: replayed="
      f"{fleet.last_recovery['replayed']} "
      f"redriven={fleet.last_recovery['redriven']} "
      f"completed={completed} failed={failed}")
PY
