#!/usr/bin/env bash
# Seeded whole-fleet crash-and-recover soak for the durable control
# plane (CPU lane).
#
# Runs ONE seeded workload twice — a clean arm straight to idle, and a
# crashed arm that checkpoints mid-traffic, submits more, is abandoned
# two ticks later with streams in every state (queued, mid-chunked-
# prefill, shipped-in-transit, adopted-and-decoding), and comes back
# via Fleet.recover — and asserts the durability invariants:
#   - every request completed OR ended in an explicit RequestFailure
#   - every completed row bit-identical to the clean arm (greedy AND
#     seeded-sampled) — journaled rng keys + redrive, not luck
#   - zero block leaks on every recovered arena
#   - decode compile counts stay 1 through recovery (restored arenas,
#     no new programs on the steady path)
#
# Usage: tools/recovery_soak.sh [SEED] [REQUESTS]
#   SEED      workload seed                  (default 0)
#   REQUESTS  requests in the workload       (default 6)
#
# The same SEED replays the identical workload + crash point
# bit-for-bit. Exits non-zero on any invariant violation.
set -euo pipefail
cd "$(dirname "$0")/.."

SEED="${1:-0}"
REQUESTS="${2:-6}"

JAX_PLATFORMS=cpu python - "$SEED" "$REQUESTS" <<'PY'
import json
import sys

import jax
# the documented jaxlib landmine: a stale persistent compile cache can
# corrupt the heap when additional paged backends compile in-process
# (ROADMAP env note) — recovery re-traces onto reset arenas, stay cold
jax.config.update("jax_enable_compilation_cache", False)

from paddle_tpu.serving.microbench import run_serving_recovery_bench

seed, requests = (int(a) for a in sys.argv[1:3])
out = run_serving_recovery_bench(seed=seed, requests=requests)
print("RECOVERY_JSON " + json.dumps(out))
assert out["serving_recovery_completed"] \
    == out["serving_recovery_requests"], "request vanished in crash"
assert out["serving_recovery_bit_identical"], \
    "rows diverged through the crash"
assert out["serving_recovery_decode_compiles"] == 1, \
    "recovery recompiled the decode block"
assert out["serving_recovery_leaks"] == 0
print(f"recovery soak OK: seed={seed} "
      f"replayed={out['serving_recovery_journal_replayed']} "
      f"redriven={out['serving_recovery_redriven']} "
      f"recover_wall_s={out['serving_recovery_recover_wall_s']} "
      f"completed={out['serving_recovery_completed']}")
PY
