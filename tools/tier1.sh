#!/usr/bin/env bash
# Tier-1 verify runner (the ROADMAP.md command, with a paper trail).
#
# Adds what the raw command doesn't record:
#   - jax/jaxlib versions stamped next to the results (the per-re-anchor
#     jaxlib-upgrade check needs to know which jaxlib produced each run);
#   - the known environment landmine printed up front: jax's persistent
#     compile cache + pytest xdist/randomly corrupts the native heap
#     when a SECOND paged step backend compiles in one process (glibc
#     double-free at exit; documented in tests/test_resilience.py).
#     This invocation passes `-p no:xdist -p no:randomly` and is immune
#     — re-check the landmine on every jaxlib upgrade.
#   - a stale-cache guard: a tests/.jax_cache accumulated across MANY
#     sessions (~140 entries, PR 7 data point) reproducibly segfaults
#     the full suite mid-GC at a later paged-backend jax.jit even with
#     the plugins disabled. Entry-count/age heuristic below wipes it
#     BEFORE the run instead of after the crash.
#
# Usage: tools/tier1.sh [extra pytest args]
# Log:   /tmp/_t1.log (flat), DOTS_PASSED echoed at the end.
set -o pipefail
cd "$(dirname "$0")/.."

# --- stale multi-session compile cache (ROADMAP heap-corruption
# landmine): wipe when the entry count says "many sessions" or the
# oldest entry says "not from today's session". A fresh worktree starts
# cache-empty, which is why seed-comparison runs never crash.
CACHE="tests/.jax_cache"
CACHE_MAX_ENTRIES="${TIER1_CACHE_MAX_ENTRIES:-100}"
CACHE_MAX_AGE_H="${TIER1_CACHE_MAX_AGE_H:-24}"
if [ -d "$CACHE" ]; then
  n=$(find "$CACHE" -type f 2>/dev/null | wc -l)
  oldest=$(find "$CACHE" -type f -printf '%T@\n' 2>/dev/null \
           | sort -n | head -1 | cut -d. -f1)
  age_h=0
  if [ -n "$oldest" ]; then
    age_h=$(( ($(date +%s) - oldest) / 3600 ))
  fi
  if [ "$n" -gt "$CACHE_MAX_ENTRIES" ] || \
     [ "$age_h" -gt "$CACHE_MAX_AGE_H" ]; then
    echo "tier1: wiping stale $CACHE ($n entries, oldest ${age_h}h old" \
         "> ${CACHE_MAX_ENTRIES}/${CACHE_MAX_AGE_H}h) — multi-session" \
         "accumulation corrupts the native heap mid-GC (ROADMAP note)"
    rm -rf "$CACHE"
  else
    echo "tier1: $CACHE ok ($n entries, oldest ${age_h}h old)"
  fi
fi

VERS=$(JAX_PLATFORMS=cpu python - <<'EOF'
import importlib.metadata as md
def v(p):
    try:
        return md.version(p)
    except md.PackageNotFoundError:
        return "unknown"
print(f"jax={v('jax')} jaxlib={v('jaxlib')}")
EOF
)
echo "tier1: $VERS"
echo "tier1: re-anchor check — re-verify the compile-cache landmine on" \
     "any jaxlib upgrade from the version above (ROADMAP env note)"
echo "tier1: landmine note — persistent compile cache + xdist/randomly" \
     "corrupts the native heap on a 2nd in-process paged-backend" \
     "compile; this runner passes -p no:xdist -p no:randomly (immune)." \
     "A STALE multi-session tests/.jax_cache can still segfault the" \
     "full suite mid-GC: on a native crash, rm -rf tests/.jax_cache" \
     "and re-run before blaming the tree. Re-check on each jaxlib" \
     "upgrade (ROADMAP env note)."

# --- autotune tuning-table provenance: kernels consult the table at
# trace time (ops/pallas/autotune.py); a stamp that disagrees with the
# running jaxlib/device kind is refused by lookup() — surface the same
# verdict here instead of letting stale block shapes pass silently.
TUNE_TABLE="${PT_TUNE_TABLE:-$HOME/.cache/paddle_tpu/tune_table.json}"
if [ -f "$TUNE_TABLE" ]; then
  JAX_PLATFORMS=cpu PT_TUNE_TABLE="$TUNE_TABLE" python - <<'EOF'
from paddle_tpu.ops.pallas import autotune as at
path = at.table_path()
table = at.load_table(path)
if table is None:
    print(f"tier1: WARNING autotune table {path} unreadable — kernels "
          "fall back to documented defaults")
else:
    ok, reason = at.stamp_matches(table.get("stamp", {}))
    n = len(table.get("entries", {}))
    if ok:
        print(f"tier1: autotune table ok ({path}, {n} entries, stamp "
              f"{table['stamp'].get('jaxlib_version')}/"
              f"{table['stamp'].get('device_kind')})")
    else:
        print(f"tier1: WARNING autotune table {path} is STALE "
              f"({reason}) — kernels fall back to documented defaults; "
              "re-run the bench autotune stage to refresh")
EOF
else
  echo "tier1: no autotune table at $TUNE_TABLE (kernels use" \
       "documented default block shapes; bench.py's autotune stage" \
       "writes one)"
fi

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly "$@" 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "tier1: $VERS" >> /tmp/_t1.log
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit $rc
