#!/usr/bin/env bash
# Tier-1 verify runner (the ROADMAP.md command, with a paper trail).
#
# Adds what the raw command doesn't record:
#   - jax/jaxlib versions stamped next to the results (the per-re-anchor
#     jaxlib-upgrade check needs to know which jaxlib produced each run);
#   - the known environment landmine printed up front: jax's persistent
#     compile cache + pytest xdist/randomly corrupts the native heap
#     when a SECOND paged step backend compiles in one process (glibc
#     double-free at exit; documented in tests/test_resilience.py).
#     This invocation passes `-p no:xdist -p no:randomly` and is immune
#     — re-check the landmine on every jaxlib upgrade.
#
# Usage: tools/tier1.sh [extra pytest args]
# Log:   /tmp/_t1.log (flat), DOTS_PASSED echoed at the end.
set -o pipefail
cd "$(dirname "$0")/.."

VERS=$(JAX_PLATFORMS=cpu python - <<'EOF'
import importlib.metadata as md
def v(p):
    try:
        return md.version(p)
    except md.PackageNotFoundError:
        return "unknown"
print(f"jax={v('jax')} jaxlib={v('jaxlib')}")
EOF
)
echo "tier1: $VERS"
echo "tier1: landmine note — persistent compile cache + xdist/randomly" \
     "corrupts the native heap on a 2nd in-process paged-backend" \
     "compile; this runner passes -p no:xdist -p no:randomly (immune)." \
     "A STALE multi-session tests/.jax_cache can still segfault the" \
     "full suite mid-GC: on a native crash, rm -rf tests/.jax_cache" \
     "and re-run before blaming the tree. Re-check on each jaxlib" \
     "upgrade (ROADMAP env note)."

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly "$@" 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "tier1: $VERS" >> /tmp/_t1.log
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit $rc
