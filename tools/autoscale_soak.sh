#!/usr/bin/env bash
# Seeded kill-and-burst autoscaling soak for the serving fleet (CPU
# lane).
#
# Replays ONE seeded loadgen trace (steady traffic, a burst episode, a
# decode-worker kill inside the burst, recovery) against an autoscaled
# paged fleet — the control loop armed with min=2/max=4 — plus the
# static-peak and static-min reference arms, and asserts the
# autoscaling invariants:
#   - every request completed OR ended in an explicit RequestFailure
#   - completed streams bit-identical to the static-peak arm, greedy
#     rows bit-identical to generate() (scale events never touch
#     token streams)
#   - zero block leaks on every surviving arena, including workers the
#     autoscaler scaled in and drained out
#   - the fleet returns to the min size after the burst clears
#   - decode compile counts stay 1 through every scale-in
#
# Usage: tools/autoscale_soak.sh [SEED] [HORIZON]
#   SEED     trace/kill schedule seed        (default 0)
#   HORIZON  trace submit window, in ticks   (default 36)
#
# The same SEED replays the identical trace+kill schedule bit-for-bit.
# Exits non-zero on any invariant violation.
set -euo pipefail
cd "$(dirname "$0")/.."

SEED="${1:-0}"
HORIZON="${2:-36}"

JAX_PLATFORMS=cpu python - "$SEED" "$HORIZON" <<'PY'
import json
import sys

import jax
# the documented jaxlib landmine: a stale persistent compile cache can
# corrupt the heap when additional paged backends compile in-process
# (ROADMAP env note); scale-ups compile fresh decode backends, so
# stay cold
jax.config.update("jax_enable_compilation_cache", False)

from paddle_tpu.serving.microbench import run_serving_autoscale_bench

seed, horizon = (int(a) for a in sys.argv[1:3])
out = run_serving_autoscale_bench(seed=seed, horizon=horizon)
print("AUTOSCALE_JSON " + json.dumps(out))
assert out["serving_autoscale_completed"] \
    + out["serving_autoscale_failed"] \
    == out["serving_autoscale_requests"], "request vanished"
assert out["serving_autoscale_bit_identical_vs_peak"], \
    "streams diverged across scale events"
assert out["serving_autoscale_greedy_matches_generate"], \
    "greedy rows diverged from generate()"
assert out["serving_autoscale_returned_to_min"], \
    "fleet did not drain back to the min size"
assert out["serving_autoscale_decode_compiles"] == 1, \
    "a scale event recompiled the decode block"
assert out["serving_autoscale_leaks"] == 0
print(f"autoscale soak OK: seed={seed} "
      f"ups={out['serving_autoscale_scale_ups']} "
      f"downs={out['serving_autoscale_scale_downs']} "
      f"peak={out['serving_autoscale_peak_size']} "
      f"end={out['serving_autoscale_end_size']} "
      f"completed={out['serving_autoscale_completed']} "
      f"failed={out['serving_autoscale_failed']}")
PY
