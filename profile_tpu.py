"""Profile the headline bench step and attribute device time
(VERDICT r2 missing #1 / weak #3: no MFU attribution existed).

Captures a jax.profiler trace of the headline Llama train step —
default the ~0.95B bf16 config (PROFILE_CONFIG=small for the 0.27B
one), post-processes the xplane with xprof into an
op-category breakdown, and writes PROFILE_r05.json + the raw trace
directory (profile_r05/) for TensorBoard.

Run on the chip:      python profile_tpu.py
Machinery test (CPU): JAX_PLATFORMS=cpu python profile_tpu.py --cpu
"""
from __future__ import annotations

import glob
import json
import os
import sys
import time

import numpy as np

OUT = os.environ.get("PROFILE_OUT", "PROFILE_r05.json")
TRACE_DIR = os.environ.get("PROFILE_TRACE_DIR", "profile_r05")


def _op_breakdown(trace_dir):
    """Parse the xplane into per-op self-time attribution using xprof:
    op_profile byCategory (device) first, the overview_page top-ops
    table as fallback (host-only traces)."""
    paths = sorted(glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True))
    if not paths:
        return None, "no xplane.pb found"

    def load(tool):
        from xprof.convert import raw_to_tool_data as rtd
        data, _ = rtd.xspace_to_tool_data([paths[-1]], tool, {})
        if isinstance(data, bytes):
            data = data.decode()
        return json.loads(data) if isinstance(data, str) else data

    err1 = None
    try:
        tree = load("op_profile")
        root = tree.get("byCategory") or {}
        cats = []
        for child in root.get("children", []):
            m = child.get("metrics") or {}
            cats.append({
                "category": child.get("name"),
                "time_fraction": round(float(m.get("time", 0.0)), 4),
                "flops_utilization": round(float(m.get("flops", 0.0)), 4),
            })
        cats.sort(key=lambda c: -c["time_fraction"])
        if cats:
            return {"source": "op_profile", "device_type":
                    tree.get("deviceType"), "categories": cats[:15]}, None
    except Exception as e:  # degraded trace: fall through to op stats
        err1 = f"op_profile: {type(e).__name__}: {e}"

    try:  # host-only / degraded trace: per-op stats table
        tables = load("framework_op_stats")
        table = tables[0] if isinstance(tables, list) else tables
        idx = {c["id"]: i for i, c in enumerate(table.get("cols", []))}
        rows = []
        for r in table.get("rows", [])[:15]:
            c = r.get("c", [])

            def val(key):
                i = idx.get(key)
                return c[i].get("v") if i is not None and i < len(c) \
                    else None

            def first_num(*keys):
                for k in keys:
                    v = val(k)
                    if v is not None:
                        return v
                return None
            rows.append({
                "where": val("host_or_device"),
                "type": val("type"),
                "op": val("operation"),
                "total_self_time": val("total_self_time"),
                "self_time_pct": first_num(
                    "device_total_self_time_percent",
                    "host_total_self_time_percent"),
                "bound_by": val("bound_by"),
            })
        rows = [r for r in rows if r["op"]]
        return {"source": "framework_op_stats", "rows": rows}, err1
    except Exception as e:
        return None, f"{err1 + '; ' if err1 else ''}" \
            f"framework_op_stats: {type(e).__name__}: {e}"


def main():
    force_cpu = "--cpu" in sys.argv
    import jax
    if force_cpu:
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    from paddle_tpu import amp, optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.llama import LlamaConfig, llama_tiny_config
    from paddle_tpu.models.llama import LlamaForCausalLM

    on_tpu = jax.devices()[0].platform != "cpu"
    which = os.environ.get("PROFILE_CONFIG", "big" if on_tpu else "tiny")
    if which == "big":
        # the headline shape (SAME object bench.py's config_big uses —
        # profiling a drifted copy would mis-attribute the BENCH number)
        from _bench_common import headline_big_config
        cfg = headline_big_config("full")
        batch, seq = 8, 2048
    elif which == "small":
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_hidden_layers=16, num_attention_heads=16,
            num_key_value_heads=16, max_position_embeddings=1024,
            tensor_parallel=False)
        batch, seq = 32, 1024
    else:
        cfg = llama_tiny_config(tensor_parallel=False)
        batch, seq = 2, 64

    paddle.seed(0)
    if which == "big":
        paddle.set_default_dtype("bfloat16")
        try:
            model = LlamaForCausalLM(cfg)
        finally:
            paddle.set_default_dtype("float32")
        opt = optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                              parameters=model.parameters(),
                              multi_precision=False)
    else:
        model = LlamaForCausalLM(cfg)
        opt = optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                              parameters=model.parameters(),
                              multi_precision=True)
        model, opt = amp.decorate(model, opt, level="O2",
                                  dtype="bfloat16")

    def loss_fn(m, b):
        ids, labels = b
        loss, _ = m(ids, labels)
        return loss

    step = TrainStep(model, loss_fn, opt)
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    batch_t = (paddle.to_tensor(ids),
               paddle.to_tensor(np.roll(ids, -1, 1).astype(np.int32)))

    for _ in range(3):          # compile + warm
        loss = step(batch_t)
    float(loss.item())

    os.makedirs(TRACE_DIR, exist_ok=True)
    with jax.profiler.trace(TRACE_DIR):
        t0 = time.perf_counter()
        for _ in range(5):
            loss = step(batch_t)
        final = float(loss.item())
        dt = (time.perf_counter() - t0) / 5

    breakdown, err = _op_breakdown(TRACE_DIR)
    from paddle_tpu.ops.pallas.flash_attention import sdpa_last_dispatch
    artifact = {
        "artifact": "PROFILE_r05",
        "chip": os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
        if on_tpu else "cpu",
        "config": {"name": which, "params": int(model.num_params()),
                   "batch": batch, "seq": seq},
        "step_ms": round(dt * 1000, 2),
        "final_loss": round(final, 4),
        "tokens_per_sec": round(batch * seq / dt, 1),
        "sdpa_dispatch": sdpa_last_dispatch(),
        "trace_dir": TRACE_DIR,
        "op_breakdown": breakdown,
        **({"breakdown_error": err} if err else {}),
    }
    with open(OUT, "w") as f:
        json.dump(artifact, f, indent=1)
    print(json.dumps(artifact)[:2000])


if __name__ == "__main__":
    main()
