"""Long-context single-chip training points (r5).

The long-context story (SURVEY §2.3 SP/CP rows) is validated
functionally by the ring/ulysses dryruns and tests, but no on-chip
number exists for long sequences on ONE chip. This probe measures the
0.27B-class Llama at long seq, full-causal vs sliding-window attention
(the splash block-sparse route skips fully-masked tiles, so the window
points also quantify the splash win at depth):

    A  seq 8192,  full causal,     b4   (32k tokens/step)
    B  seq 8192,  window 1024,     b4
    C  seq 16384, window 1024,     b2   (the depth point)

Every point is AOT-prechecked against the 15.2 GB budget (a refusal
costs one compile — the r5 window-1 OOM-wedge lesson) and HBM is
released between points. Merged into BENCH_TPU_MEASURED_r05.json under
"longctx"; one merge per point so a mid-run wedge keeps earlier points.
"""
from __future__ import annotations

import gc
import json
import os

from _bench_common import configure_jax, merge_artifact

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "BENCH_TPU_MEASURED_r05.json")


def main():
    jax = configure_jax()
    on_tpu = jax.devices()[0].platform != "cpu"
    chip = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e").lower() \
        if on_tpu else "cpu"

    import bench
    from paddle_tpu.models.llama import LlamaConfig, llama_tiny_config

    peak = bench.PEAK_FLOPS.get(chip, 1e12)

    def cfg(seq, window):
        if not on_tpu:
            return llama_tiny_config(tensor_parallel=False,
                                     max_position_embeddings=seq,
                                     sliding_window=window)
        return LlamaConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_hidden_layers=16, num_attention_heads=16,
            num_key_value_heads=16, max_position_embeddings=seq,
            tensor_parallel=False, recompute=True,
            recompute_granularity="full", scan_layers=True,
            dtype="bfloat16", sliding_window=window)

    if on_tpu:
        points = [("s8192_causal", 8192, None, 4),
                  ("s8192_w1024", 8192, 1024, 4),
                  ("s16384_w1024", 16384, 1024, 2)]
    else:
        points = [("smoke_s128_w32", 128, 32, 2)]

    result = {}
    for name, seq, window, batch in points:
        gc.collect()
        try:
            jax.clear_caches()
        except Exception:
            pass
        gc.collect()
        try:
            r = bench._bench_train(
                cfg(seq, window), batch=batch, seq=seq, steps=4,
                warmup=1, peak=peak, multi_precision=False,
                hbm_limit=15.2e9 if on_tpu else None)
            result[name] = {
                "tokens_per_sec": r["tokens_per_sec"], "mfu": r["mfu"],
                "step_ms": r["step_ms"], "batch": batch, "seq": seq,
                "window": window}
            if window is not None and on_tpu:
                # flops_per_token charges FULL causal attention; a
                # windowed step executes ~12*L*h*window instead — the
                # honest utilization divides by work actually done
                c = cfg(seq, window)
                attn_full = 12 * c.num_hidden_layers * c.hidden_size * seq
                attn_win = 12 * c.num_hidden_layers * c.hidden_size \
                    * min(seq, window)
                f_full = peak * r["mfu"] / r["tokens_per_sec"]
                f_win = f_full - attn_full + attn_win
                result[name]["mfu_windowed_work"] = round(
                    r["tokens_per_sec"] * f_win / peak, 4)
        except Exception as e:
            result[name] = {"error": f"{type(e).__name__}: {e}"[:300]}
        print("LONGCTX " + json.dumps({name: result[name]}), flush=True)
        merge_artifact(OUT, "longctx", dict(result), chip)


if __name__ == "__main__":
    main()
