"""13B-scale readiness check WITHOUT multi-chip hardware (VERDICT r1 #4).

AOT-compiles the full fused Llama-13B TP×PP train step over a VIRTUAL
v5p-32 mesh (32 CPU host devices; AOT lowering is hardware-independent)
with abstract spec-only weights — no host memory for 13B params — and
records XLA's own per-device memory/cost estimates. Asserts the config
fits v5p HBM with the chosen remat/donation policy.

Writes SCALE_r03.json (override: SCALE_OUT) and prints it.

Usage:  python scale_check.py   (forces JAX_PLATFORMS=cpu, 32 devices)
"""
from __future__ import annotations

import json
import os
import sys
import time

N_DEV = int(os.environ.get("SCALE_DEVICES", "32"))
V5P_HBM_BYTES = 95 * 1024**3       # v5p: 95 GiB HBM per chip
OUT = os.environ.get("SCALE_OUT", "SCALE_r03.json")


def main():
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        flags += f" --xla_force_host_platform_device_count={N_DEV}"
    # XLA-CPU's all-reduce-promotion pass crashes (CHECK failure) cloning
    # bf16 all-reduce reducers that carry sharding annotations (psum
    # inside a partial-auto shard_map). The pass only exists because CPU
    # lacks native bf16 reductions — irrelevant here: this program is
    # compiled for its memory/cost analysis, never executed.
    if "all-reduce-promotion" not in flags:
        flags += " --xla_disable_hlo_passes=all-reduce-promotion"
    os.environ["XLA_FLAGS"] = flags.strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_13b_config
    from paddle_tpu.distributed.mesh import set_current_mesh
    from paddle_tpu.utils.scale import (abstract_init, attach_shardings,
                                        abstract_state_specs)

    assert len(jax.devices()) == N_DEV, \
        f"need {N_DEV} virtual devices, got {len(jax.devices())}"
    # v5p-32: TP=8 inside a host group (ICI-rich axis), PP=4 across
    pp, mp = 4, 8
    mesh = Mesh(np.array(jax.devices()).reshape(pp, mp), ("pp", "mp"))
    set_current_mesh(mesh)

    cfg = llama_13b_config(
        tensor_parallel=True, pipeline_parallel=True, recompute=True,
        recompute_granularity="selective",   # matmul outputs saved: the
        # memory headroom (95 GiB HBM) buys recompute-free dots -> MFU
        pp_num_microbatches=8, max_position_embeddings=4096,
        # interleaved VPP, one layer per chunk (L=40, S=4 -> V=10):
        # bubble (S-1)/(M·V+S-1) = 3/83 = 3.6% vs 27% non-interleaved
        # (PIPELINE_BUBBLE_r03.json)
        virtual_pp=10)
    batch, seq = 8, 4096

    t0 = time.time()
    with abstract_init(dtype="bfloat16"):
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
    attach_shardings(model, mesh)
    n_params = sum(int(np.prod(p._value.shape))
                   for _, p in model.named_parameters())
    build_s = time.time() - t0

    # bf16 weights + bf16 moments (the bench big-config policy: no
    # fp32 master copies), per-layer remat via cfg.recompute
    opt = optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                          parameters=model.parameters(),
                          multi_precision=False)

    def loss_fn(m, b):
        ids, labels = b
        loss, _ = m(ids, labels)
        return loss

    step = TrainStep(model, loss_fn, opt)
    # mirror shard_optimizer's default placement for the slot specs
    step._build()
    pvals = {n: t._value for n, t in step._ptensors.items()}
    opt._slots = abstract_state_specs(opt.functional_state(), pvals)[
        "slots"]

    repl = NamedSharding(mesh, P())
    dp_batch = NamedSharding(mesh, P())  # batch replicated over pp×mp
    ids_spec = jax.ShapeDtypeStruct((batch, seq), jnp.int32,
                                    sharding=dp_batch)
    # place the small concrete buffers (rope tables) on the mesh
    for _, b in model.named_buffers():
        b._update_value(jax.device_put(b._value, repl))

    t0 = time.time()
    lowered = step.lower((ids_spec, ids_spec))
    lower_s = time.time() - t0
    t0 = time.time()
    compiled = lowered.compile()
    compile_s = time.time() - t0

    ma = compiled.memory_analysis()
    ca = compiled.cost_analysis() or {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    # memory_analysis of an SPMD executable reports PER-DEVICE figures
    per_dev = {
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
    }
    # donation aliases params+opt state in place; live set =
    # args (params/opt/batch) + temps (activations etc.)
    peak = per_dev["argument_bytes"] + per_dev["temp_bytes"] \
        + per_dev["output_bytes"] - per_dev["alias_bytes"]
    fits = peak <= V5P_HBM_BYTES

    flops = float(ca.get("flops", 0.0))
    v5p_peak_flops = 459e12
    step_time_lower_bound_s = flops / v5p_peak_flops if flops else None

    result = {
        "artifact": os.path.splitext(os.path.basename(OUT))[0],
        "model": "llama-13b",
        "n_params": int(n_params),
        "mesh": {"pp": pp, "mp": mp, "devices": N_DEV,
                 "target": "v5p-32 (virtual; CPU AOT)"},
        "config": {"batch": batch, "seq": seq,
                   "virtual_pp": cfg.virtual_pp,
                   "pp_bubble": round(
                       (pp - 1) / (cfg.pp_num_microbatches
                                   * cfg.virtual_pp + pp - 1), 4),
                   "microbatches": cfg.pp_num_microbatches,
                   "dtype": "bfloat16",
                   "remat": cfg.recompute_granularity
                   if cfg.recompute else "none",
                   "optimizer": "AdamW bf16 states, no master copies",
                   "donation": "params+opt_state donated"},
        "per_device": per_dev,
        "per_device_peak_estimate_bytes": int(peak),
        "per_device_peak_estimate_gib": round(peak / 1024**3, 2),
        "v5p_hbm_gib": 95,
        "fits_v5p_hbm": bool(fits),
        "hlo": {
            "flops_per_step_per_device": flops,
            "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
            "step_time_lower_bound_s_at_v5p_peak":
                round(step_time_lower_bound_s, 3)
                if step_time_lower_bound_s else None,
        },
        "timings_s": {"abstract_build": round(build_s, 1),
                      "lower": round(lower_s, 1),
                      "compile": round(compile_s, 1)},
    }
    with open(OUT, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    sync_readme(result)
    if not fits:
        print(f"FAIL: {result['per_device_peak_estimate_gib']} GiB "
              f"> 95 GiB v5p HBM", file=sys.stderr)
        sys.exit(1)


def sync_readme(result, readme="README.md"):
    """Regenerate the README scale paragraph from the artifact so docs
    can never disagree with the JSON (VERDICT r2 weak #2: a hand-typed
    20.6 GiB survived a 38.4 GiB artifact refresh)."""
    begin = "<!-- SCALE_DOC_BEGIN"
    end = "<!-- SCALE_DOC_END -->"
    try:
        text = open(readme).read()
    except OSError:
        return
    i = text.find(begin)
    j = text.find(end)
    nl = text.find("\n", i)
    if i < 0 or j < 0 or nl < 0 or j <= nl:   # malformed/reordered markers
        return
    i = nl + 1                           # keep the marker line itself
    pd = result["per_device"]
    gib = 1024 ** 3
    block = (
        f"{result['n_params'] / 1e9:.1f}B params on a virtual "
        f"{result['mesh']['target'].split()[0]} "
        f"(pp={result['mesh']['pp']} × mp={result['mesh']['mp']}), "
        f"batch {result['config']['batch']} × seq "
        f"{result['config']['seq']},\n"
        f"bfloat16, {result['config']['remat']} remat, donated "
        f"params+opt_state: **{result['per_device_peak_estimate_gib']} "
        f"GiB peak\nper device vs {result['v5p_hbm_gib']} GiB v5p HBM — "
        f"{'fits' if result['fits_v5p_hbm'] else 'DOES NOT FIT'}.** "
        f"(temp {pd['temp_bytes'] / gib:.1f} GiB dominates;\n"
        f"arguments {pd['argument_bytes'] / gib:.1f} GiB, alias "
        f"{pd['alias_bytes'] / gib:.1f} GiB.)\n")
    with open(readme, "w") as f:
        f.write(text[:i] + block + text[j:])


if __name__ == "__main__":
    main()
