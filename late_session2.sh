#!/bin/bash
# Trimmed session-2 for a LATE healthy window (<90 min before the
# quiet cutoff). VERDICT-priority order, hard stop enforced:
#   moe A/B (EP: zero on-chip evidence) -> ernie_moe workload ->
#   decode sweep -> bert_base -> resnet50 (as fit).
# Usage: bash late_session2.sh <hard_stop_epoch_seconds>
set -x
cd "$(dirname "$0")"
HARD_STOP=${1:?usage: late_session2.sh <hard_stop_epoch>}
touch .watch_stop
mkdir -p /tmp/w2

left() { echo $(( HARD_STOP - $(date +%s) )); }
budget() { local want=$1 l=$(left); echo $(( l - 90 < want ? l - 90 : want )); }

run_stage() { # name want_seconds cmd...
    local name=$1 want=$2; shift 2
    local b=$(budget "$want")
    [ "$b" -lt 240 ] && { echo "skip $name: $(left)s left"; return 1; }
    timeout -s INT -k 30 "$b" "$@" > "/tmp/w2/$name.log" 2>&1
    tail -2 "/tmp/w2/$name.log"
}

run_stage moe 900 python moe_breakdown.py
run_stage ernie 1200 python bench_workloads.py ernie_moe
line=$(grep '^WORKLOAD ' /tmp/w2/ernie.log 2>/dev/null | tail -1 | sed 's/^WORKLOAD //')
if [ -n "$line" ]; then
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python3 - "$line" <<'EOF'
import json, sys
out = "WORKLOADS_r05.json"
d = json.load(open(out))
d["ernie_moe"] = json.loads(sys.argv[1])
json.dump(d, open(out, "w"), indent=1)
EOF
fi
run_stage decode 900 python sweep_decode.py
for w in bert_base resnet50 sdxl_unet; do
    run_stage "$w" 900 python bench_workloads.py "$w" || break
    line=$(grep '^WORKLOAD ' "/tmp/w2/$w.log" 2>/dev/null | tail -1 | sed 's/^WORKLOAD //')
    [ -n "$line" ] && env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python3 - "$w" "$line" <<'EOF'
import json, sys
out = "WORKLOADS_r05.json"
d = json.load(open(out))
d[sys.argv[1]] = json.loads(sys.argv[2])
json.dump(d, open(out, "w"), indent=1)
EOF
done
echo "late_session2 done with $(left)s to hard stop"
