#!/bin/bash
# Late-window measurement: when a healthy tunnel appears too close to
# the end-of-round driver window for the full tpu_session.sh ladder.
# Usage: bash late_window.sh <hard_stop_epoch_seconds>
# Runs bench (tiny->small->big ladder, artifacts merged incrementally)
# then as many workloads as fit, and GUARANTEES nothing of ours holds
# the chip past the hard stop (the driver needs a quiet tunnel).
set -x
cd "$(dirname "$0")"
HARD_STOP=${1:?usage: late_window.sh <hard_stop_epoch>}
touch .watch_stop

left() { echo $(( HARD_STOP - $(date +%s) )); }

L=$(left)
[ "$L" -lt 300 ] && { echo "too little time"; exit 1; }
BENCH_BUDGET=$(( L > 4200 ? 3900 : L - 240 ))
BENCH_TPU_DEADLINE_S=$BENCH_BUDGET BENCH_TOTAL_BUDGET_S=$BENCH_BUDGET \
    timeout -s INT -k 30 $(( BENCH_BUDGET + 60 )) python bench.py \
    | tee /tmp/bench_last.json
python - <<'EOF'
import json, os
try:
    new = json.load(open("/tmp/bench_last.json"))
except Exception:
    raise SystemExit
if new.get("chip") != "v5e":
    raise SystemExit
out = "BENCH_TPU_MEASURED_r05.json"
NEVER_CARRY = {"config_errors", "partial", "stage_s",
               "carried_from_previous"}
try:
    old = json.load(open(out)) if os.path.exists(out) else {}
except Exception:
    old = {}
if old.get("chip") == "v5e":
    carried = []
    for k, v in old.items():
        if k not in NEVER_CARRY and new.get(k) is None:
            new[k] = v
            carried.append(k)
    if carried:
        new["carried_from_previous"] = sorted(carried)
    head = new.get("config_big") or new.get("config_small")
    if head:
        new["value"] = head["tokens_per_sec"]
        new["mfu"] = head["mfu"]
        new["vs_baseline"] = round(head["mfu"] / 0.45, 4)
json.dump(new, open(out + ".tmp", "w"), indent=1)
os.replace(out + ".tmp", out)
EOF

for w in ernie_moe resnet50 bert_base sdxl_unet; do
    L=$(left)
    [ "$L" -lt 700 ] && break
    line=$(timeout -s INT -k 30 $(( L - 120 < 600 ? L - 120 : 600 )) \
           python bench_workloads.py "$w" 2>&1 \
           | grep '^WORKLOAD ' | tail -1 | sed 's/^WORKLOAD //')
    [ -z "$line" ] && continue
    python - "$w" "$line" <<'EOF'
import json, os, sys
out = "WORKLOADS_r05.json"
d = json.load(open(out)) if os.path.exists(out) else {
    "artifact": "WORKLOADS_r05", "chip": "v5e"}
d[sys.argv[1]] = json.loads(sys.argv[2])
json.dump(d, open(out, "w"), indent=1)
EOF
done
# absolutely nothing of ours may touch the chip after this
pkill -f "python bench" 2>/dev/null
exit 0
