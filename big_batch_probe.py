"""Does the 0.95B headline config fit and win at batch 16? (r5)

bench.py's ladder tries full-remat b8 first and stops on success, so
b16 — potentially higher MFU from larger per-dispatch matmuls — has
never been attempted. This standalone probe AOT-prechecks b16 (and
b12 as fallback) against the 15.2 GB v5e budget and runs whichever
fits; a refused config costs one compile, never an OOM (the r5
window-1 wedge lesson). If a larger batch beats b8's 52.18% MFU, flip
bench.py's ladder to try it first next round.

Merged into BENCH_TPU_MEASURED_r05.json under "big_batch_probe".
"""
from __future__ import annotations

import gc
import json
import os

from _bench_common import configure_jax, headline_big_config, merge_artifact

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "BENCH_TPU_MEASURED_r05.json")


def main():
    jax = configure_jax()
    on_tpu = jax.devices()[0].platform != "cpu"
    chip = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e").lower() \
        if on_tpu else "cpu"

    import bench

    peak = bench.PEAK_FLOPS.get(chip, 1e12)
    result = {}
    batches = (16, 12) if on_tpu else (2,)
    seq = 2048 if on_tpu else 64

    def cfg():
        if on_tpu:
            return headline_big_config("full")
        from paddle_tpu.models.llama import llama_tiny_config
        return llama_tiny_config(tensor_parallel=False)

    for b in batches:
        gc.collect()
        try:
            jax.clear_caches()
        except Exception:
            pass
        gc.collect()
        try:
            r = bench._bench_train(
                cfg(), batch=b, seq=seq, steps=8, warmup=2, peak=peak,
                multi_precision=False,
                hbm_limit=15.2e9 if on_tpu else None)
            result[f"b{b}"] = {"mfu": r["mfu"],
                               "tokens_per_sec": r["tokens_per_sec"],
                               "step_ms": r["step_ms"]}
            print("BIG_BATCH " + json.dumps({f"b{b}": result[f"b{b}"]}),
                  flush=True)
            merge_artifact(OUT, "big_batch_probe", dict(result), chip)
            break        # largest fitting batch answers the question
        except Exception as e:
            result[f"b{b}"] = {"error": f"{type(e).__name__}: {e}"[:300]}
            print("BIG_BATCH " + json.dumps({f"b{b}": result[f"b{b}"]}),
                  flush=True)
            merge_artifact(OUT, "big_batch_probe", dict(result), chip)
    print("BIG_BATCH " + json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
