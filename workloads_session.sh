#!/bin/bash
# Measure the non-Llama BASELINE workloads on the chip; merge each
# point into WORKLOADS_r05.json as it completes (a later tunnel wedge
# keeps earlier points).
cd "$(dirname "$0")"
OUT=WORKLOADS_r05.json
# ernie_moe first: EP is the one parallelism axis with zero on-chip
# perf evidence (VERDICT r4 missing #4) — if the tunnel wedges
# mid-session the highest-priority point must already be merged.
for w in ernie_moe resnet50 bert_base sdxl_unet; do
    line=$(timeout -s INT -k 30 600 python bench_workloads.py "$w" 2>&1 \
           | grep '^WORKLOAD ' | tail -1 | sed 's/^WORKLOAD //')
    [ -z "$line" ] && line="{\"workload\": \"$w\", \"error\": \"no output (timeout/crash)\"}"
    python - "$w" "$line" <<'EOF'
import json, os, sys
out = "WORKLOADS_r05.json"
d = json.load(open(out)) if os.path.exists(out) else {
    "artifact": "WORKLOADS_r05", "chip": "v5e",
    "note": ("throughput for the BASELINE.json workloads beyond the "
             "Llama headline (bench.py); utilization_vs_peak uses "
             "XLA cost-analysis FLOPs, see bench_workloads.py")}
d[sys.argv[1]] = json.loads(sys.argv[2])
json.dump(d, open(out, "w"), indent=1)
EOF
    echo "done $w: $line"
done
