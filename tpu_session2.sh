#!/bin/bash
# Second-window measurement session (r5). Assumes tpu_session.sh's bench
# stage already banked the headline (BENCH_TPU_MEASURED_r05.json,
# 0.95B @ 52.18% MFU) in window 1 before the tunnel's compile service
# degraded (all three workload children sat idle-waiting on compile
# RPCs after bench's two runtime RESOURCE_EXHAUSTED stages — the r02
# wedge signature). Probe ONCE before running:
#   timeout -s INT -k 30 90 python -c "import jax; print(jax.devices())" || exit 1
# Risk-ordered: cheap/known-fast compiles first, the runtime-OOM-risk
# splash A/B dead last, every stage merge-incremental + stderr kept.
set -x
cd "$(dirname "$0")"
touch .watch_stop
mkdir -p /tmp/w2

# 1. decode sweep (VERDICT #5): 0.27B Llama decode — config_small's
#    compile family, proven fast in window 1; replaces the decode
#    stage the bench child lost to RESOURCE_EXHAUSTED.
timeout -s INT -k 30 1000 python sweep_decode.py \
    > /tmp/w2/decode.log 2>&1
tail -3 /tmp/w2/decode.log

# Dead-tunnel fast abort: stage 1's tool merges "decode_sweep" into the
# artifact within its first minutes when healthy. If after the full
# stage window the key is still absent, every later stage would burn
# its timeout against the same wedge (window-1 pattern: three children
# idle-waiting 600s each) — return to quiet instead. env-stripped
# python: the check itself must not dial axon.register().
if ! env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python3 - <<'EOF'
import json, sys
try:
    d = json.load(open("BENCH_TPU_MEASURED_r05.json"))
except Exception:
    sys.exit(1)
sys.exit(0 if "decode_sweep" in d else 1)
EOF
then
    echo "SESSION2 ABORT: decode stage produced no merge - tunnel dead"
    touch .session2_aborted
    exit 1
fi

# 2. MoE breakdown + dispatch A/B (VERDICT #4): pure-jnp/pallas block
#    shapes (no full-model compile); EP's first on-chip evidence.
timeout -s INT -k 30 1000 python moe_breakdown.py \
    > /tmp/w2/moe.log 2>&1
tail -3 /tmp/w2/moe.log

# 3. workloads (VERDICT #3), ERNIE first, windows sized to the slow
#    compile observed in window 1 (600s was not enough; stderr kept so
#    a SIGINT traceback shows WHERE a timed-out child was stuck).
for spec in ernie_moe:1500 bert_base:1000 resnet50:1500 sdxl_unet:1500; do
    w=${spec%%:*}; budget=${spec##*:}
    timeout -s INT -k 30 "$budget" python bench_workloads.py "$w" \
        > "/tmp/w2/$w.log" 2>&1
    line=$(grep '^WORKLOAD ' "/tmp/w2/$w.log" | tail -1 | sed 's/^WORKLOAD //')
    [ -z "$line" ] && line="{\"workload\": \"$w\", \"error\": \"no output (timeout/crash); see /tmp/w2/$w.log\"}"
    python - "$w" "$line" <<'EOF'
import json, os, sys
out = "WORKLOADS_r05.json"
d = json.load(open(out)) if os.path.exists(out) else {
    "artifact": "WORKLOADS_r05", "chip": "v5e"}
d[sys.argv[1]] = json.loads(sys.argv[2])
json.dump(d, open(out, "w"), indent=1)
EOF
    echo "done $w: $line"
done

# 4. profile re-capture after the run_steps lever (VERDICT #2 tail)
timeout -s INT -k 30 700 python profile_tpu.py > /tmp/w2/profile.log 2>&1
tail -3 /tmp/w2/profile.log

# 5. on-chip kernel validation tests
PT_TPU_TESTS=1 timeout -s INT -k 30 560 python -m pytest \
    tests/test_pallas_tpu.py -q > /tmp/w2/tputests.log 2>&1
tail -5 /tmp/w2/tputests.log

# 6. big-batch probe: does full-remat b16 (or b12) fit and beat b8's
#    52.18% MFU? Precheck-guarded; a refusal costs one compile.
timeout -s INT -k 30 900 python big_batch_probe.py > /tmp/w2/bigbatch.log 2>&1
tail -3 /tmp/w2/bigbatch.log

# 7. splash A/B retry, LAST + reduced batch: window 1's b8 attempt
#    passed the 15.2 GB AOT precheck but RESOURCE_EXHAUSTED at runtime
#    (splash bwd's true footprint exceeds the estimate) — b4 halves
#    activations; a repeat OOM can only cost this final stage.
timeout -s INT -k 30 900 python splash_ab.py > /tmp/w2/splash.log 2>&1
tail -3 /tmp/w2/splash.log

# 8. long-context single-chip points: seq 8192 causal vs window-1024,
#    seq 16384 windowed (precheck-guarded, merge-per-point).
timeout -s INT -k 30 1200 python longctx_probe.py > /tmp/w2/longctx.log 2>&1
tail -3 /tmp/w2/longctx.log

touch .session2_done
