"""Headline benchmark: Llama pretrain step throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

vs_baseline = achieved MFU / 0.45 (the BASELINE.json north-star MFU target;
no reference throughput numbers were recoverable — see BASELINE.md)."""
from __future__ import annotations

import json
import time

import numpy as np

# chip peak bf16 FLOP/s by generation (public specs)
PEAK_FLOPS = {"v5e": 197e12, "v5litepod": 197e12, "v4": 275e12,
              "v5p": 459e12, "v6e": 918e12, "cpu": 1e12}


def main():
    import os
    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e").lower() if on_tpu \
        else "cpu"
    peak = PEAK_FLOPS.get(gen, 197e12 if on_tpu else 1e12)

    import paddle_tpu as paddle
    from paddle_tpu import amp, nn, optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          intermediate_size=2816, num_hidden_layers=16,
                          num_attention_heads=16, num_key_value_heads=16,
                          max_position_embeddings=1024,
                          tensor_parallel=False)
        batch, seq, steps, warmup = 8, 1024, 12, 3
    else:  # smoke path for CPU dev runs
        from paddle_tpu.models.llama import llama_tiny_config
        cfg = llama_tiny_config(tensor_parallel=False)
        batch, seq, steps, warmup = 2, 64, 4, 1

    model = LlamaForCausalLM(cfg)
    opt = optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                          parameters=model.parameters(),
                          multi_precision=True)
    model, opt = amp.decorate(model, opt, level="O2", dtype="bfloat16")

    def loss_fn(m, b):
        ids, labels = b
        loss, _ = m(ids, labels)
        return loss

    step = TrainStep(model, loss_fn, opt)
    ids = np.random.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)
    batch_t = (paddle.to_tensor(ids), paddle.to_tensor(labels))

    for _ in range(warmup):
        loss = step(batch_t)
    float(loss.item())  # sync

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(batch_t)
    final = float(loss.item())  # sync
    dt = time.perf_counter() - t0

    tokens = batch * seq * steps
    tok_per_s = tokens / dt
    flops_per_token = model.flops_per_token(seq)
    mfu = tok_per_s * flops_per_token / peak
    n_params = model.num_params()

    print(json.dumps({
        "metric": "llama_pretrain_tokens_per_sec_per_chip",
        "value": round(tok_per_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
        "mfu": round(mfu, 4),
        "model_params": int(n_params),
        "chip": gen,
        "batch": batch, "seq": seq,
        "final_loss": round(final, 4),
        "step_ms": round(dt / steps * 1000, 2),
    }))


if __name__ == "__main__":
    main()
