"""Headline benchmark: Llama pretrain step throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

vs_baseline = achieved MFU / 0.45 (the BASELINE.json north-star MFU target;
no reference throughput numbers were recoverable — see BASELINE.md).

Robustness contract (VERDICT r1 #1): the parent process NEVER initializes a
jax backend itself. The measurement runs in a child process under a hard
deadline; if the axon TPU tunnel is wedged (backend init hangs or raises
UNAVAILABLE — both observed), the child is killed and the parent emits a
JSON line with "tpu_unavailable": true plus a CPU AOT compile-stats
fallback, exiting 0 either way.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# env knobs through the utils/flags helpers (the PR 4/5 migration
# pattern — uniform empty-value leniency). Importing the package does
# NOT initialize a jax backend (verified: xla_bridge._backends stays
# empty), so the parent's never-init contract holds.
from paddle_tpu.utils.flags import env_float, env_int, env_str

# chip peak bf16 FLOP/s by generation (public specs)
PEAK_FLOPS = {"v5e": 197e12, "v5litepod": 197e12, "v4": 275e12,
              "v5p": 459e12, "v6e": 918e12, "cpu": 1e12}

TPU_ATTEMPTS = env_int("BENCH_TPU_ATTEMPTS", 2)
# r3 learning: 480s deadline-killed the ~1B config mid-compile (its
# scan_layers compile + 3-batch ladder needs ~10-15 min end to end);
# the 90s probe already bounds the wedged-tunnel cost, and per-stage
# BENCH_JSON emission preserves earlier stages if the child dies
TPU_DEADLINE_S = env_float("BENCH_TPU_DEADLINE_S", 1100)
CPU_DEADLINE_S = env_float("BENCH_CPU_DEADLINE_S", 420)
COMMS_DEADLINE_S = env_float("BENCH_COMMS_DEADLINE_S", 240)
PASSES_DEADLINE_S = env_float("BENCH_PASSES_DEADLINE_S", 240)
OBS_DEADLINE_S = env_float("BENCH_OBS_DEADLINE_S", 240)
SERVING_SPEC_DEADLINE_S = env_float("BENCH_SERVING_SPEC_DEADLINE_S", 240)
SERVING_TP_DEADLINE_S = env_float("BENCH_SERVING_TP_DEADLINE_S", 300)
SERVING_QUANT_DEADLINE_S = env_float("BENCH_SERVING_QUANT_DEADLINE_S",
                                     300)
SERVING_MEGA_DEADLINE_S = env_float("BENCH_SERVING_MEGA_DEADLINE_S", 300)
SERVING_FRONTDOOR_DEADLINE_S = env_float(
    "BENCH_SERVING_FRONTDOOR_DEADLINE_S", 300)
SERVING_FAILOVER_DEADLINE_S = env_float(
    "BENCH_SERVING_FAILOVER_DEADLINE_S", 300)
SERVING_DISAGG_DEADLINE_S = env_float(
    "BENCH_SERVING_DISAGG_DEADLINE_S", 300)
SERVING_PREFIXCACHE_DEADLINE_S = env_float(
    "BENCH_SERVING_PREFIXCACHE_DEADLINE_S", 300)
SERVING_AUTOSCALE_DEADLINE_S = env_float(
    "BENCH_SERVING_AUTOSCALE_DEADLINE_S", 300)
SERVING_RECOVERY_DEADLINE_S = env_float(
    "BENCH_SERVING_RECOVERY_DEADLINE_S", 300)
AUTOTUNE_DEADLINE_S = env_float("BENCH_AUTOTUNE_DEADLINE_S", 300)
# cheap tunnel-health probe (tiny matmul) before committing to a heavy
# child: a wedged tunnel then costs PROBE_DEADLINE_S, not TPU_DEADLINE_S
PROBE_DEADLINE_S = env_float("BENCH_PROBE_DEADLINE_S", 90)


def _bench_train(model_cfg, batch, seq, steps, warmup, peak,
                 multi_precision=True, hbm_limit=None):
    """Measure one-chip training throughput for one config. Runs inside the
    child process (backend already chosen). ``hbm_limit``: AOT-compile
    first and SKIP execution (raise with the numbers) when XLA's memory
    estimate exceeds it — an OOM config then costs one compile, not a
    crashed child/tunnel (VERDICT r2 missing #3)."""
    import paddle_tpu as paddle
    from paddle_tpu import amp, optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.llama import LlamaForCausalLM

    paddle.seed(0)
    if getattr(model_cfg, "dtype", "float32") == "bfloat16":
        # pure-bf16 build: params AND Adam moments in bf16
        # (2 bytes x 3 per param) — the memory budget that fits ~1B on
        # one 16 GB v5e chip; no AMP wrapper needed. finally: a failed
        # build (e.g. OOM) must not leak the bf16 default into later
        # stages of this child
        paddle.set_default_dtype("bfloat16")
        try:
            model = LlamaForCausalLM(model_cfg)
        finally:
            paddle.set_default_dtype("float32")
        opt = optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                              parameters=model.parameters(),
                              multi_precision=False)
    else:
        model = LlamaForCausalLM(model_cfg)
        opt = optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                              parameters=model.parameters(),
                              multi_precision=multi_precision)
        model, opt = amp.decorate(model, opt, level="O2",
                                  dtype="bfloat16")

    def loss_fn(m, b):
        ids, labels = b
        loss, _ = m(ids, labels)
        return loss

    step = TrainStep(model, loss_fn, opt)
    ids = np.random.randint(0, model_cfg.vocab_size,
                            (batch, seq)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)
    batch_t = (paddle.to_tensor(ids), paddle.to_tensor(labels))

    if hbm_limit is not None:
        compiled = step.lower(batch_t).compile()
        ma = compiled.memory_analysis()
        est = (getattr(ma, "temp_size_in_bytes", 0)
               + getattr(ma, "argument_size_in_bytes", 0)
               + getattr(ma, "output_size_in_bytes", 0)
               - getattr(ma, "alias_size_in_bytes", 0))
        if est <= 0:
            # an inert guard must not masquerade as a passed check —
            # the caller decides whether to run un-prechecked
            raise RuntimeError(
                "AOT memory precheck unavailable on this backend "
                "(memory_analysis lacks size fields); refusing the "
                "un-prechecked run at this batch size")
        if est > hbm_limit:
            raise RuntimeError(
                f"AOT memory precheck: {est / 1e9:.2f} GB estimated > "
                f"{hbm_limit / 1e9:.2f} GB limit; skipping execution")

    for _ in range(warmup):
        loss = step(batch_t)
    float(loss.item())  # sync

    # the timed window runs as ONE lax.scan dispatch: per-step host
    # round-trips through the tunnel showed up as 9.3% device IDLE in
    # PROFILE_r03; scan removes them entirely
    try:
        loss = step.run_steps(batch_t, steps)   # compile the scan prog
        float(loss.item())
        t0 = time.perf_counter()
        loss = step.run_steps(batch_t, steps)
        final = float(loss.item())  # sync
        dt = time.perf_counter() - t0
    except Exception:
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(batch_t)
        final = float(loss.item())  # sync
        dt = time.perf_counter() - t0

    tok_per_s = batch * seq * steps / dt
    mfu = tok_per_s * model.flops_per_token(seq) / peak
    return {"tokens_per_sec": round(tok_per_s, 1),
            "mfu": round(mfu, 4),
            "model_params": int(model.num_params()),
            "batch": batch, "seq": seq,
            "final_loss": round(final, 4),
            "step_ms": round(dt / steps * 1000, 2)}


def _bench_decode(model_cfg, batch, prompt, new_tokens):
    """KV-cache autoregressive decode throughput (jitted decode step)."""
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaForCausalLM

    paddle.seed(0)
    model = LlamaForCausalLM(model_cfg)
    ids = paddle.to_tensor(np.random.randint(
        0, model_cfg.vocab_size, (batch, prompt)).astype(np.int32))
    # warmup with IDENTICAL shapes (same cache length) so the timed run
    # reuses the compiled prefill + decode step
    model.generate(ids, max_new_tokens=new_tokens)
    t0 = time.perf_counter()
    out = model.generate(ids, max_new_tokens=new_tokens)
    assert out.shape[1] == prompt + new_tokens
    dt = time.perf_counter() - t0
    return {"decode_tokens_per_sec": round(batch * new_tokens / dt, 1),
            "decode_batch": batch, "decode_prompt": prompt,
            "decode_new_tokens": new_tokens}


def _bench_continuous_decode(model_cfg, num_slots=4, decode_block=8,
                             long_new=96, short_new=8):
    """Continuous-batching vs static-batch decode on a mixed-length
    staggered request stream — the serving headline. Static batching
    rides every row until the slowest request finishes; the slot pool
    retires/refills rows as they complete, so aggregate useful tokens/s
    is strictly higher on ragged traffic. Returns both numbers plus the
    ratio so the trajectory is tracked every round."""
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaForCausalLM
    from paddle_tpu.serving import ContinuousBatchingEngine, Server

    paddle.seed(0)
    model = LlamaForCausalLM(model_cfg)
    rs = np.random.RandomState(0)
    # arrival order interleaves one long-budget request per slot group:
    # the static baseline's every group then rides to 96 tokens while
    # three short rows sit finished (the continuous engine refills them)
    lens = [16, 4, 8, 4, 16, 4, 8, 4]
    news = [long_new, short_new, short_new, short_new] * 2
    bucket = 16
    max_len = bucket + max(news)
    prompts = [rs.randint(0, model_cfg.vocab_size, (l,)).astype(np.int32)
               for l in lens]
    useful = sum(news)

    engine = ContinuousBatchingEngine(
        model, num_slots=num_slots, max_len=max_len,
        decode_block=decode_block, prompt_buckets=(bucket,))

    def engine_pass():
        engine.reset()
        srv = Server(engine)
        for p, mn in zip(prompts, news):
            srv.submit(p, max_new_tokens=mn)
        srv.run_until_idle()
        return srv

    engine_pass()                         # compile warmup
    t0 = time.perf_counter()
    srv = engine_pass()
    dt_engine = time.perf_counter() - t0

    def static_pass():
        for g in range(0, len(prompts), num_slots):
            chunk = prompts[g:g + num_slots]
            mns = news[g:g + num_slots]
            lmax = max(len(p) for p in chunk)
            ids = np.zeros((len(chunk), lmax), np.int32)
            am = np.zeros((len(chunk), lmax), np.int32)
            for i, p in enumerate(chunk):
                ids[i, lmax - len(p):] = p
                am[i, lmax - len(p):] = 1
            out = model.generate(paddle.to_tensor(ids),
                                 max_new_tokens=max(mns),
                                 attention_mask=paddle.to_tensor(am))
            np.asarray(out.numpy())       # sync

    static_pass()                         # compile warmup
    t0 = time.perf_counter()
    static_pass()
    dt_static = time.perf_counter() - t0

    stats = srv.stats()
    return {
        "decode_tokens_per_sec": round(useful / dt_engine, 1),
        "decode_static_tokens_per_sec": round(useful / dt_static, 1),
        "decode_speedup_vs_static": round(dt_static / dt_engine, 3),
        "decode_mode": "continuous_batching",
        "decode_requests": len(prompts),
        "decode_slots": num_slots,
        "decode_slot_occupancy": stats["slot_occupancy"],
        "decode_compile_count": stats["decode_compile_count"],
        # time-to-first-token percentiles over the timed stream — the
        # user-facing latency half of the serving headline (tokens/s
        # alone hides admission queueing + prefill stalls)
        "decode_ttft_p50_ms": round(stats["ttft_p50_s"] * 1000, 2),
        "decode_ttft_p95_ms": round(stats["ttft_p95_s"] * 1000, 2),
    }


def _bench_paged_serving(model_cfg, num_slots=4, block_size=16,
                         decode_block=8, prefix_len=96, tail_len=8,
                         requests=6, max_new=16):
    """Paged-KV serving A/B on a shared-prefix workload: every request
    repeats one system prompt with a distinct tail (the prefix cache's
    target case). Measures (a) prefix-cache hit rate + per-slot KV HBM
    vs the dense engine, and (b) chunked-vs-whole prefill interference:
    max per-tick latency with a per-tick prefill token budget (chunks
    interleave with decode) against unbudgeted whole-prompt prefill —
    chunking bounds the decode-latency spike a long prompt causes."""
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaForCausalLM
    from paddle_tpu.serving import (ContinuousBatchingEngine, Scheduler,
                                    Server)

    paddle.seed(0)
    model = LlamaForCausalLM(model_cfg)
    rs = np.random.RandomState(0)
    prefix = rs.randint(0, model_cfg.vocab_size,
                        (prefix_len,)).astype(np.int32)
    prompts = [np.concatenate([prefix, rs.randint(
        0, model_cfg.vocab_size, (tail_len,)).astype(np.int32)])
        for _ in range(requests)]
    max_len = block_size * (
        -(-(prefix_len + tail_len + max_new) // block_size))
    chunk = block_size
    # size the arena for the workload, not the worst case: the shared
    # prefix blocks exist ONCE, each slot only adds its tail + decode
    # blocks (+1 trash, +2 slack) — this is where the HBM-per-slot
    # reduction vs the dense (num_slots * max_len) layout comes from;
    # a transient shortage just re-queues the request
    per_req = -(-(prefix_len + tail_len + max_new - 1) // block_size)
    shared_blocks = prefix_len // block_size
    num_blocks = 1 + per_req + (num_slots - 1) * (
        per_req - shared_blocks) + 2

    engine = ContinuousBatchingEngine(
        model, num_slots=num_slots, max_len=max_len,
        decode_block=decode_block, paged=True, block_size=block_size,
        num_blocks=num_blocks, prefill_chunk=chunk)

    def run(budget):
        engine.reset()
        srv = Server(engine, Scheduler(prefill_token_budget=budget))
        for i, p in enumerate(prompts):
            # staggered arrivals: later prompts prefill WHILE earlier
            # requests decode — the interference case
            srv.submit(p, max_new_tokens=max_new, arrival_step=3 * i)
        srv.run_until_idle()
        return srv

    run(chunk)                              # compile warmup
    srv_chunked = run(chunk)
    st_chunked = srv_chunked.stats()
    srv_whole = run(None)
    st_whole = srv_whole.stats()

    dense_bytes = (2 * model_cfg.num_hidden_layers * max_len
                   * model_cfg.num_key_value_heads
                   * (model_cfg.hidden_size
                      // model_cfg.num_attention_heads) * 4)

    out = {
        "serving_paged_prefix_hit_rate":
            st_chunked["prefix_cache_hit_rate"],
        "serving_paged_kv_bytes_per_slot":
            st_chunked["kv_bytes_per_slot"],
        "serving_dense_kv_bytes_per_slot": dense_bytes,
        "serving_paged_tokens_per_sec": st_chunked["tokens_per_sec"],
        "serving_paged_max_tick_ms_chunked":
            round(st_chunked["max_tick_s"] * 1000, 2),
        "serving_paged_max_tick_ms_whole":
            round(st_whole["max_tick_s"] * 1000, 2),
        "serving_paged_ttft_p95_ms_chunked":
            round(st_chunked["ttft_p95_s"] * 1000, 2),
        "serving_paged_ttft_p95_ms_whole":
            round(st_whole["ttft_p95_s"] * 1000, 2),
        "serving_paged_compile_counts": [
            st_chunked["decode_compile_count"],
            engine.prefill_compile_count()],
    }

    # int8 KV point: measured dequant error of a served stream must sit
    # under the runtime-queryable bound (the EQuARX contract applied to
    # the cache)
    engine8 = ContinuousBatchingEngine(
        model, num_slots=num_slots, max_len=max_len,
        decode_block=decode_block, paged=True, block_size=block_size,
        num_blocks=num_blocks,       # same arena size as the fp32 A/B
        prefill_chunk=chunk, kv_int8=True)
    srv8 = Server(engine8, Scheduler(prefill_token_budget=chunk))
    for p in prompts[:2]:
        srv8.submit(p, max_new_tokens=max_new)
    srv8.run_until_idle()
    out["serving_paged_kv_int8_bytes_per_slot"] = \
        engine8.backend.kv_bytes_per_slot()
    out["serving_paged_kv_int8_error_bound"] = \
        round(engine8.kv_error_bound(), 6)
    return out


def _bench_resilience(model_cfg, num_slots=4, decode_block=8,
                      requests=10, max_new=24, fault_rate=0.01):
    """Resilience A/B: the same request stream clean vs with a
    ``fault_rate`` injected step-failure probability (the
    ``serving.step_block`` site, seeded — the schedule is identical
    every round). Reports the throughput + p95 latency cost of riding
    the retry/backoff path and the resilience counters, so a policy
    regression (e.g. retries stopping masking transient faults, or the
    breaker tripping on background noise) shows up as a number."""
    import paddle_tpu as paddle
    from paddle_tpu.serving import (ContinuousBatchingEngine,
                                    ResilienceConfig, Server)
    from paddle_tpu.utils import faults

    paddle.seed(0)
    from paddle_tpu.models.llama import LlamaForCausalLM
    model = LlamaForCausalLM(model_cfg)
    rs = np.random.RandomState(0)
    lens = [4 + (i % 3) * 6 for i in range(requests)]
    prompts = [rs.randint(0, model_cfg.vocab_size, (l,)).astype(np.int32)
               for l in lens]
    engine = ContinuousBatchingEngine(
        model, num_slots=num_slots, max_len=16 + max_new,
        decode_block=decode_block, prompt_buckets=(16,))
    res_cfg = ResilienceConfig(retry_attempts=3, retry_backoff_s=0.002,
                               breaker_threshold=32)

    def run():
        engine.reset()
        srv = Server(engine, resilience=res_cfg)
        for i, p in enumerate(prompts):
            srv.submit(p, max_new_tokens=max_new, arrival_step=i)
        srv.run_until_idle()
        return srv

    run()                                   # compile warmup
    t0 = time.perf_counter()
    srv_clean = run()
    dt_clean = time.perf_counter() - t0
    st_clean = srv_clean.stats()

    faults.configure(f"serving.step_block:p={fault_rate}", seed=0)
    try:
        t0 = time.perf_counter()
        srv_faulty = run()
        dt_faulty = time.perf_counter() - t0
    finally:
        faults.clear()
    st_faulty = srv_faulty.stats()
    useful = requests * max_new
    return {
        "serving_resilience_tokens_per_sec_clean":
            round(useful / dt_clean, 1),
        "serving_resilience_tokens_per_sec_faulty":
            round(useful / dt_faulty, 1),
        "serving_resilience_p95_latency_ms_clean":
            round(st_clean["latency_p95_s"] * 1000, 2),
        "serving_resilience_p95_latency_ms_faulty":
            round(st_faulty["latency_p95_s"] * 1000, 2),
        "serving_resilience_fault_rate": fault_rate,
        "serving_resilience_step_failures": st_faulty["step_failures"],
        "serving_resilience_retries": st_faulty["retries"],
        "serving_resilience_requests_failed":
            st_faulty["requests_failed"],
        "serving_resilience_completed_faulty":
            st_faulty["requests_completed"],
        # the clean pass pins the inertness contract in the bench too
        "serving_resilience_clean_counters_zero":
            st_clean["step_failures"] == 0 == st_clean["retries"],
    }


def _child_tpu():
    """Runs under the default (axon TPU) platform. Benches a 0.2B config
    and the largest Llama that fits one chip in bf16, reports the Pallas
    dispatch route, prints one JSON dict."""
    import jax
    try:
        # persistent compile cache: a repeat bench run (the driver's
        # end-of-round capture after a mid-round session) skips the
        # multi-minute big-config compile entirely if the backend
        # supports serialized executables
        jax.config.update("jax_compilation_cache_dir",
                          env_str("PT_JAX_CACHE_DIR",
                                  "/root/.pt_jax_cache") or
                          "/root/.pt_jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    except Exception:
        pass
    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    gen = env_str("PALLAS_AXON_TPU_GEN", "v5e").lower() if on_tpu \
        else "cpu"
    peak = PEAK_FLOPS.get(gen, 197e12 if on_tpu else 1e12)

    from paddle_tpu.models.llama import LlamaConfig, llama_tiny_config

    def _isolated(fn, label):
        """One config must not take down the others' results (a v5e HBM
        OOM on the big config previously killed the whole child)."""
        try:
            return fn(), None
        except Exception as e:
            msg = f"{type(e).__name__}: {e}"
            return None, f"{label}: {msg[:600]}"

    t_child0 = time.perf_counter()
    stage_s = {}

    def _staged(fn, label):
        """_isolated + wall-clock accounting per stage, so a deadline
        kill is attributable (r3: the window vanished into stages with
        no on-record timing)."""
        t0 = time.perf_counter()
        out, err = _isolated(fn, label)
        stage_s[label] = round(time.perf_counter() - t0, 1)
        return out, err

    def _emit(small, big, decode, errors):
        """One BENCH_JSON line from whatever has finished so far; the
        parent keeps the LAST line, so emitting after every stage means a
        deadline kill mid-child can no longer lose the headline."""
        from paddle_tpu.ops.pallas import flash_attention as fa
        head = big or small
        if head is None:
            return
        stage_s["child_total"] = round(time.perf_counter() - t_child0, 1)
        print("BENCH_JSON " + json.dumps({
            "metric": "llama_pretrain_tokens_per_sec_per_chip",
            "value": head["tokens_per_sec"],
            "unit": "tokens/s",
            "vs_baseline": round(head["mfu"] / 0.45, 4),
            "mfu": head["mfu"],
            "chip": gen,
            "device_kind": dev.device_kind,
            "device_count": jax.device_count(),
            "sdpa_dispatch": fa.sdpa_last_dispatch(),
            "config_small": small,
            "config_big": big,
            "stage_s": dict(stage_s),
            **({"config_errors": errors} if errors else {}),
            **(decode or {}),
            **{k: head[k] for k in ("model_params", "batch", "seq",
                                    "final_loss", "step_ms")},
        }), flush=True)

    errors = []
    if on_tpu:
        # stage 0, "tiny": a llama-tiny step that compiles in seconds —
        # its ONLY job is to stamp a chip:"v5e" BENCH_JSON line on the
        # record within the first minute of a healthy window, so even a
        # driver window that dies during the 0.27B compile leaves a TPU
        # artifact (VERDICT r3 weak #1 / next #3). The line is
        # overwritten by every later stage's emit.
        tiny, err = _staged(lambda: _bench_train(
            llama_tiny_config(tensor_parallel=False), batch=4, seq=128,
            steps=4, warmup=1, peak=peak), "tiny")
        if err:
            errors.append(err)
        if tiny is not None:
            tiny["note"] = ("tunnel-liveness stage, not a perf point; "
                            "see config_small/config_big")
            _emit(tiny, None, None, errors)
        cfg_small = LlamaConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_hidden_layers=16, num_attention_heads=16,
            num_key_value_heads=16, max_position_embeddings=1024,
            tensor_parallel=False)
        # batch 32 measured best on v5e: 24.4k tok/s, 22.65% MFU
        # (sweep: b8 20.8%, b16 22.2%, b32 22.65%; seq 2048 regresses)
        small, err = _staged(lambda: _bench_train(
            cfg_small, batch=32, seq=1024, steps=10, warmup=3, peak=peak),
            "small")
        if small is None:
            small = tiny  # keep the v5e stamp as the fallback headline
        if err:
            errors.append(err)
        _emit(small, None, None, errors)
        # ~0.95B params; bf16 optimizer states (multi_precision off) +
        # per-layer remat + fused head CE (default-on). Every batch size
        # is AOT-memory-prechecked (15.2/16 GB v5e budget) so an
        # over-budget config costs one compile, never an OOM crash.
        def big_cfg(gran):
            # scan_layers inside: the XLA program holds ONE layer body —
            # small enough not to stress the tunnel's compile helper
            # (r02's unrolled big-config compile crashed it)
            from _bench_common import headline_big_config
            return headline_big_config(gran)
        big = None
        # full-remat b8 first: the known-good 48.97%-MFU headline shape
        # — lock it in before experiments. Smallest batch runs even if
        # the backend can't report memory stats (r02 behavior).
        for gran, bb in (("full", 8), ("full", 4), ("full", 2)):
            limit = 15.2e9 if bb > 2 else None
            big, err = _staged(
                lambda g=gran, b=bb, lm=limit: _bench_train(
                    big_cfg(g), batch=b, seq=2048, steps=8, warmup=2,
                    peak=peak, multi_precision=False, hbm_limit=lm),
                f"big-{gran}-b{bb}")
            if err:
                errors.append(err)
            if big is not None:
                big["remat"] = gran
                break
        _emit(small, big, None, errors)
        # r5 window-1 lesson: stages leak HBM into their successors —
        # big-splash and decode both hit runtime RESOURCE_EXHAUSTED with
        # three stages' buffers resident, and the OOM crashes degraded
        # the tunnel's compile service for every child after (the r02
        # wedge signature, re-observed). Free executables + their held
        # buffers between the remaining stages.
        import gc

        def _release_hbm():
            gc.collect()
            try:
                jax.clear_caches()   # compiled programs pin donated bufs
            except Exception:
                pass
            gc.collect()
        _release_hbm()
        # upside experiment: selective remat executes ~16% fewer FLOPs
        # per step (CPU AOT: 6.80e12 vs 8.09e12) = higher MFU at equal
        # step time, but holds more live activations — b8 estimates
        # 42 GB (never fits v5e), so try b4 behind the precheck; one
        # failed compile is the max cost, and the full-remat headline
        # above is already on the record
        if big is not None:
            sel, err = _staged(lambda: _bench_train(
                big_cfg("selective"), batch=4, seq=2048, steps=8,
                warmup=2, peak=peak, multi_precision=False,
                hbm_limit=15.2e9), "big-selective-b4")
            if err:
                errors.append(err)
            if sel is not None and sel["mfu"] > big["mfu"]:
                sel["remat"] = "selective"
                big = sel
        _emit(small, big, None, errors)
        # sdpa kernel A/B on the headline shape: PROFILE_r03 charges the
        # equal-heads jax_flash route 20.5% of self-time plus a 5.7%
        # HBM-bound broadcast_in_dim in its bwd; splash (block-sparse
        # CausalMask, skips fully-masked tiles) may beat it — measure,
        # keep the winner, and record both so the choice is on-artifact
        if big is not None:
            _release_hbm()
            os.environ["PT_SDPA_PREFER"] = "splash"
            try:
                # 14.5 GB, tighter than the 15.2 run limit: splash-bwd's
                # true footprint EXCEEDS the AOT estimate (r5 window-1:
                # est <=15.2 passed, runtime RESOURCE_EXHAUSTED — and an
                # on-chip OOM crash can wedge the tunnel, r02 mode), so
                # an underestimated config must be refused, not risked.
                lim = 14.5e9 if big["batch"] > 2 else None
                sp, err = _staged(lambda: _bench_train(
                    big_cfg(big.get("remat", "full")), batch=big["batch"],
                    seq=2048, steps=8, warmup=2, peak=peak,
                    multi_precision=False, hbm_limit=lim), "big-splash")
            finally:
                os.environ.pop("PT_SDPA_PREFER", None)
            if err:
                errors.append(err)
            if sp is not None:
                # attribute the A/B to the block config that produced
                # it (tuned/env/default + effective sizes) — the
                # autotune-era contract for sdpa numbers
                from paddle_tpu.ops.pallas import flash_attention as _fa
                sp["sdpa_block_choice"] = _fa.last_block_choice()
                big["sdpa_ab"] = {"jax_flash": big["mfu"],
                                  "splash": sp["mfu"]}
                if sp["mfu"] > big["mfu"]:
                    sp["remat"] = big.get("remat")
                    sp["sdpa_ab"] = big["sdpa_ab"]
                    sp["sdpa"] = "splash"
                    big = sp
        _emit(small, big, None, errors)
        # decode runs LAST: it is the least informative stage for the
        # MFU contract, and r3 showed it can eat the deadline window
        # the ~1B headline config needed
        _release_hbm()
        decode, err = _staged(lambda: _bench_decode(
            cfg_small, batch=8, prompt=128, new_tokens=128), "decode")
        if err:
            errors.append(err)
        decode = decode or {}
        # the continuous-batching engine owns the decode_tokens_per_sec
        # headline; the old fixed-batch decode point moves to its own
        # key. A failed engine stage must still leave the headline key
        # present (null), not silently drop the round's decode record.
        if "decode_tokens_per_sec" in decode:
            decode["decode_fixed_batch_tokens_per_sec"] = \
                decode.pop("decode_tokens_per_sec")
        _release_hbm()
        serve, err = _staged(lambda: _bench_continuous_decode(
            cfg_small, num_slots=8), "decode-continuous")
        if err:
            errors.append(err)
        decode.update(serve if serve is not None
                      else {"decode_tokens_per_sec": None})
        _release_hbm()
        paged, err = _staged(lambda: _bench_paged_serving(cfg_small),
                             "serving-paged")
        if err:
            errors.append(err)
        decode.update(paged if paged is not None
                      else {"serving_paged_prefix_hit_rate": None})
        _release_hbm()
        resil, err = _staged(lambda: _bench_resilience(cfg_small),
                             "serving-resilience")
        if err:
            errors.append(err)
        decode.update(resil if resil is not None
                      else {"serving_resilience_tokens_per_sec_faulty":
                            None})
        _release_hbm()
        # tensor-parallel decode over the window's REAL chips: the
        # microbench itself records a skip when the window owns one
        # chip (the usual case) — the key stays on the record either way
        from paddle_tpu.serving.microbench import run_serving_tp_bench
        tp, err = _staged(run_serving_tp_bench, "serving-tp")
        if err:
            errors.append(err)
        decode.update(tp if tp is not None
                      else {"serving_tp_bit_identical": None})
        _release_hbm()
        # speculative decode on the REAL chip: where the (S, k+1)
        # verify forward re-reads weights once instead of k+1 times per
        # emitted token — the 2-3x decode headline target lives here
        from paddle_tpu.serving.microbench import run_serving_spec_bench
        sp_dec, err = _staged(run_serving_spec_bench, "serving-spec")
        if err:
            errors.append(err)
        decode.update(sp_dec if sp_dec is not None
                      else {"serving_spec_speedup": None})
        _release_hbm()
        # fused decode-layer megakernel on the REAL chip: the Pallas
        # decode-layer kernel dispatches here (kernel_calls > 0), so
        # the tokens/s delta is the HBM-round-trip win, not overhead
        from paddle_tpu.serving.microbench import \
            run_serving_megakernel_bench
        mega, err = _staged(run_serving_megakernel_bench,
                            "serving-megakernel")
        if err:
            errors.append(err)
        decode.update(mega if mega is not None
                      else {"serving_megakernel_bit_identical": None})
        _release_hbm()
        # multi-tenant front door on the REAL chip: WFQ shares,
        # preemption + bit-identical resume, per-priority TTFT
        from paddle_tpu.serving.microbench import \
            run_serving_frontdoor_bench
        fd, err = _staged(run_serving_frontdoor_bench,
                          "serving-frontdoor")
        if err:
            errors.append(err)
        decode.update(fd if fd is not None
                      else {"serving_frontdoor_bit_identical": None})
        _release_hbm()
        # disaggregated prefill/decode fleet on the REAL chip: handoff
        # wire bytes, fleet-wide prefix hit rate, disagg-vs-unified
        # TTFT/tokens/s (the hardware-pool split claim lives here)
        from paddle_tpu.serving.microbench import \
            run_serving_disagg_bench
        dis, err = _staged(run_serving_disagg_bench, "serving-disagg")
        if err:
            errors.append(err)
        decode.update(dis if dis is not None
                      else {"serving_disagg_bit_identical": None})
        _release_hbm()
        # fleet failure domains on the REAL chip: kill-one-decode-
        # worker A/B over the socket transport (redrive latency +
        # goodput under worker loss are the chip claims)
        from paddle_tpu.serving.microbench import \
            run_serving_failover_bench
        fo, err = _staged(run_serving_failover_bench,
                          "serving-failover")
        if err:
            errors.append(err)
        decode.update(fo if fo is not None
                      else {"serving_failover_bit_identical": None})
        _release_hbm()
        # fleet-wide KV prefix cache on the REAL chip: cold vs warm-
        # local vs warm-remote TTFT ladder + bytes-moved-vs-flops-
        # saved (the fetch-beats-prefill claim is a chip claim too)
        from paddle_tpu.serving.microbench import \
            run_serving_prefixcache_bench
        pfx, err = _staged(run_serving_prefixcache_bench,
                           "serving-prefixcache")
        if err:
            errors.append(err)
        decode.update(pfx if pfx is not None
                      else {"serving_prefixcache_bit_identical": None})
        _release_hbm()
        # block-size autotune sweep on the REAL chip (flash/splash
        # blocks + the CPU-honest knobs, persisted per device kind)
        from paddle_tpu.ops.pallas.autotune import run_autotune
        tune, err = _staged(run_autotune, "autotune")
        if err:
            errors.append(err)
        decode.update(tune if tune is not None
                      else {"autotune_entries": None})
        _emit(small, big, decode, errors)
        if small is None and big is None:
            raise RuntimeError("every config failed: " + "; ".join(errors))
    else:
        cfg = llama_tiny_config(tensor_parallel=False)
        small = _bench_train(cfg, batch=2, seq=64, steps=4, warmup=1,
                             peak=peak)
        decode = _bench_continuous_decode(
            llama_tiny_config(tensor_parallel=False))
        _emit(small, None, decode, errors)


def _child_cpu():
    """TPU-unavailable fallback: CPU smoke throughput + AOT compile cost
    stats for the 0.2B config, so the round still records a real artifact."""
    import jax
    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    from paddle_tpu.jit import TrainStep
    from paddle_tpu import amp, optimizer
    from paddle_tpu.models.llama import llama_tiny_config, LlamaForCausalLM

    # the decode headline runs FIRST: a small continuous-batching
    # stream vs static-batch generate, so the serving trajectory is
    # tracked every round like training tok/s. First so no earlier
    # stage's buffers/contention skew the A/B (errors must not cost
    # the pretrain headline). The model is a step up from llama-tiny:
    # at tiny scale a decode step is ~0.5 ms and the static baseline's
    # single fused scan wins on dispatch alone — the utilization
    # headroom only shows once compute matters.
    try:
        from paddle_tpu.models.llama import LlamaConfig
        serve_cfg = LlamaConfig(
            vocab_size=512, hidden_size=256, intermediate_size=768,
            num_hidden_layers=4, num_attention_heads=8,
            num_key_value_heads=8, max_position_embeddings=256,
            tensor_parallel=False)
        decode = _bench_continuous_decode(serve_cfg)
    except Exception as e:
        decode = {"decode_tokens_per_sec": None,
                  "decode_error": f"{type(e).__name__}: {e}"[:300]}
    try:
        decode.update(_bench_paged_serving(serve_cfg))
    except Exception as e:
        decode.update({"serving_paged_prefix_hit_rate": None,
                       "serving_paged_error":
                       f"{type(e).__name__}: {e}"[:300]})
    try:
        decode.update(_bench_resilience(serve_cfg))
    except Exception as e:
        decode.update({"serving_resilience_tokens_per_sec_faulty": None,
                       "serving_resilience_error":
                       f"{type(e).__name__}: {e}"[:300]})

    cfg = llama_tiny_config(tensor_parallel=False)
    smoke = _bench_train(cfg, batch=2, seq=64, steps=4, warmup=1, peak=1e12)

    # AOT compile the 0.2B single-chip step on the CPU backend and pull
    # XLA's cost model numbers (flops/bytes) — hardware-independent
    paddle.seed(0)
    cfg2 = llama_tiny_config(tensor_parallel=False)
    model = LlamaForCausalLM(cfg2)
    opt = optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())
    model, opt = amp.decorate(model, opt, level="O2", dtype="bfloat16")

    def loss_fn(m, b):
        ids, labels = b
        loss, _ = m(ids, labels)
        return loss

    step = TrainStep(model, loss_fn, opt)
    ids = paddle.to_tensor(
        np.zeros((2, 64), np.int32))
    lowered = step.lower((ids, ids))
    compiled = lowered.compile()
    cost = compiled.cost_analysis() or {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}

    print("BENCH_JSON " + json.dumps({
        "metric": "llama_pretrain_tokens_per_sec_per_chip",
        "value": smoke["tokens_per_sec"],
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "chip": "cpu",
        "device_kind": jax.devices()[0].device_kind,
        "device_count": jax.device_count(),
        "aot_step_flops": float(cost.get("flops", -1.0)),
        "aot_bytes_accessed": float(cost.get("bytes accessed", -1.0)),
        **decode,
        **{k: smoke[k] for k in ("model_params", "batch", "seq",
                                 "final_loss", "step_ms")},
    }))


def _run_child(mode: str, deadline: float):
    """Run this script in child mode; returns parsed JSON dict or None.
    The child emits BENCH_JSON after every completed stage — the LAST
    line wins, and a deadline kill still salvages the partial result."""
    env = dict(os.environ)
    if mode in ("--child-cpu", "--child-comms", "--child-passes",
                "--child-observability", "--child-serving-tp",
                "--child-serving-spec", "--child-serving-quant",
                "--child-serving-megakernel",
                "--child-serving-frontdoor", "--child-serving-disagg",
                "--child-serving-prefixcache",
                "--child-serving-autoscale",
                "--child-serving-recovery", "--child-autotune"):
        env["JAX_PLATFORMS"] = "cpu"
    if mode in ("--child-comms", "--child-serving-tp"):
        # simulated 2x4 mesh on the CPU lane
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    stdout, stderr, rc = "", "", "killed"
    # deadline → SIGINT first (KeyboardInterrupt lets the axon client
    # release its exclusive chip claim; a hard kill mid-compile wedges
    # the tunnel for everyone after — observed twice this round), only
    # then SIGKILL
    import signal
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), mode], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        try:
            stdout, stderr = proc.communicate(timeout=deadline)
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            proc.send_signal(signal.SIGINT)
            try:
                stdout, stderr = proc.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                stdout, stderr = proc.communicate()
            rc = "killed"   # a deadline kill, however gracefully it went
    except BaseException:
        # ANY other escape (KeyboardInterrupt to the parent, ...) must
        # not leak a child holding the exclusive chip claim
        proc.kill()
        proc.communicate()
        raise
    stdout, stderr = stdout or "", stderr or ""
    result = None
    for line in stdout.splitlines():
        if line.startswith("BENCH_JSON "):
            try:
                result = json.loads(line[len("BENCH_JSON "):])
            except json.JSONDecodeError:
                pass  # SIGKILL mid-flush truncated this line; keep the
                      # last complete one
    if result is not None:
        if rc == "killed":
            result["partial"] = "deadline killed the child mid-stage"
        elif rc != 0:
            # child crashed after emitting a stage result (e.g. the
            # compile helper hard-killed it) — keep the salvage but say so
            result["partial"] = f"child crashed rc={rc} after this stage"
            result["crash_tail"] = (stdout + stderr)[-500:]
        return result, None
    if rc == "killed":
        return None, "deadline exceeded (backend init or compile hang)"
    tail = (stdout + stderr)[-2000:]
    return None, f"rc={rc}: {tail}"


def _last_measured_tpu():
    """Provenance pointer for a cpu-fallback artifact: the most recent
    SELF-reported on-chip measurement (clearly labeled as recorded, not
    live — the fallback's own numbers stay the CPU ones)."""
    here = os.path.dirname(os.path.abspath(__file__))
    for name in ("BENCH_TPU_MEASURED_r05.json", "BENCH_TPU_MEASURED_r04.json",
                 "BENCH_TPU_MEASURED_r03.json"):
        path = os.path.join(here, name)
        if os.path.exists(path):
            break
    try:
        with open(path) as f:
            d = json.load(f)
        return {"source": os.path.basename(path), "chip": d.get("chip"),
                "value": d.get("value"), "mfu": d.get("mfu"),
                "config_small": d.get("config_small"),
                "config_big": d.get("config_big"),
                "decode_tokens_per_sec": d.get("decode_tokens_per_sec"),
                "note": "recorded mid-round on-chip measurement, NOT "
                        "this run"}
    except (OSError, json.JSONDecodeError):
        return None


def _child_comms():
    """comms stage: the hierarchical/quantized collective microbench
    (distributed/collectives/) over 8 simulated CPU devices. The round
    owns one chip, so there is no real multi-chip ICI to time — the
    stage pins wire-format bytes, algorithmic bandwidth and the
    quantized-vs-fp32 error contract every round, and becomes the comm
    headline the day a multi-chip window exists."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.distributed.collectives import run_comms_bench
    out = run_comms_bench(
        size_mb=env_float("BENCH_COMMS_MB", 2))
    print("BENCH_JSON " + json.dumps(out), flush=True)


def _attach_stage(result, key, mode, deadline_s, budget_s=None):
    """Merge an auxiliary child stage into the headline JSON (own child
    so a wedged stage can never cost the training headline). Strictly
    additive: with the wall budget nearly spent the stage is SKIPPED
    rather than risking the outer `timeout` killing the parent before
    the already-measured result prints."""
    deadline = deadline_s if budget_s is None \
        else min(deadline_s, budget_s - 15)
    if deadline < 30:
        result[key] = {"skipped": "wall budget exhausted"}
        return result
    out, err = _run_child(mode, deadline)
    result[key] = out if out is not None else {"error": (err or "")[:300]}
    return result


def _attach_comms(result, budget_s=None):
    return _attach_stage(result, "comms", "--child-comms",
                         COMMS_DEADLINE_S, budget_s)


def _child_passes():
    """passes stage: the jaxpr fusion-pass pipeline microbench
    (passes/microbench.py) on the CPU backend. Pins eqn-count
    reduction, compile-time delta and step-time A/B of the
    cascaded-reduction fusion every round — non-null like the comms
    stage; the on-chip HBM win rides the same flag (PT_FUSION_PASSES)
    when a TPU window exists."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.passes.microbench import run_passes_bench
    out = run_passes_bench(
        rows=env_int("BENCH_PASSES_ROWS", 256),
        vocab=env_int("BENCH_PASSES_VOCAB", 2048))
    print("BENCH_JSON " + json.dumps(out), flush=True)


def _attach_passes(result, budget_s=None):
    return _attach_stage(result, "passes", "--child-passes",
                         PASSES_DEADLINE_S, budget_s)


def _child_observability():
    """observability stage: the serving stream with metrics + request
    tracing + flight recorder fully armed vs disarmed
    (observability/microbench.py, CPU lane). Pins the <2%-enabled /
    ~0%-disabled overhead contract every round, plus proof the
    artifacts exist: metric families sampled, request/host spans and
    tick markers in one loadable merged chrome trace."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.observability.microbench import run_observability_bench
    out = run_observability_bench(
        requests=env_int("BENCH_OBS_REQUESTS", 8),
        max_new=env_int("BENCH_OBS_MAX_NEW", 24))
    print("BENCH_JSON " + json.dumps(out), flush=True)


def _attach_observability(result, budget_s=None):
    return _attach_stage(result, "observability", "--child-observability",
                         OBS_DEADLINE_S, budget_s)


def _child_serving_spec():
    """serving-spec stage: the draft-verify engine (serving/spec.py)
    A/B'd against the plain slot-pool engine on a repetitive-
    continuation workload (serving/microbench.py) — pins spec-vs-
    baseline decode tokens/s (CPU-lane gate: >= 1.3x), bit-identity,
    acceptance rate and mean accepted tokens/step every round. The
    2-3x decode target rides the same SpecConfig on the TPU child."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.serving.microbench import run_serving_spec_bench
    out = run_serving_spec_bench(
        requests=env_int("BENCH_SERVING_SPEC_REQUESTS", 8),
        max_new=env_int("BENCH_SERVING_SPEC_MAX_NEW", 64),
        k=env_int("BENCH_SERVING_SPEC_K", 8))
    print("BENCH_JSON " + json.dumps(out), flush=True)


def _attach_serving_spec(result, budget_s=None):
    return _attach_stage(result, "serving-spec", "--child-serving-spec",
                         SERVING_SPEC_DEADLINE_S, budget_s)


def _child_serving_quant():
    """serving-quant stage: the bandwidth-true quantized paged engine
    (int8 KV arena + weight-only int8 decode weights, dequant inside
    the read/gemm) A/B'd against the fp32 paged engine
    (serving/microbench.py) — pins quant-vs-fp32 decode tokens/s,
    bytes-read/step from the metrics registry (~3.5x fewer), both
    error bounds and the compile-count pin every round. On the CPU
    lane the tokens/s delta is an overhead record; the HBM-bandwidth
    win rides the same QuantConfig on the TPU child."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.serving.microbench import run_serving_quant_bench
    out = run_serving_quant_bench(
        requests=env_int("BENCH_SERVING_QUANT_REQUESTS", 8),
        max_new=env_int("BENCH_SERVING_QUANT_MAX_NEW", 48),
        weights=env_str("BENCH_SERVING_QUANT_WEIGHTS", "int8"))
    print("BENCH_JSON " + json.dumps(out), flush=True)


def _attach_serving_quant(result, budget_s=None):
    return _attach_stage(result, "serving-quant", "--child-serving-quant",
                         SERVING_QUANT_DEADLINE_S, budget_s)


def _child_serving_megakernel():
    """serving-megakernel stage: the decode-layer fusion pass + fused
    decode-layer call (passes/fusion_decode.py +
    ops/pallas/decode_layer.py) A/B'd against the plain paged+int8-KV
    engine (serving/microbench.py) — pins fused-vs-unfused bit-identity,
    tokens/s, the no-hidden-state-transient jaxpr walk, the per-layer
    rewrite count and the compile-count pin every round. On the CPU
    lane the fused body is the captured unfused jaxpr (structure pin);
    the VMEM-residency win rides the same flag on the TPU child, where
    the Pallas megakernel dispatches."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.serving.microbench import run_serving_megakernel_bench
    out = run_serving_megakernel_bench(
        requests=env_int("BENCH_SERVING_MEGA_REQUESTS", 8),
        max_new=env_int("BENCH_SERVING_MEGA_MAX_NEW", 32))
    print("BENCH_JSON " + json.dumps(out), flush=True)


def _attach_serving_megakernel(result, budget_s=None):
    return _attach_stage(result, "serving-megakernel",
                         "--child-serving-megakernel",
                         SERVING_MEGA_DEADLINE_S, budget_s)


def _child_serving_frontdoor():
    """serving-frontdoor stage: the multi-tenant traffic layer
    (serving/frontend.py) on the paged engine — pins measured
    per-tenant throughput shares vs the configured WFQ weights (gate:
    within 10%) on a saturated 3-tenant workload, priority preemption
    (count, the evicted request still completing bit-identical to an
    uninterrupted run), TTFT p50/p95 split by priority with a
    preemption-on/off A/B, and the decode/prefill compile-count pin
    every round. All fields non-null on the CPU lane; the TPU child
    stages the same workload."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.serving.microbench import run_serving_frontdoor_bench
    out = run_serving_frontdoor_bench(
        requests_per_tenant=env_int("BENCH_SERVING_FRONTDOOR_REQUESTS",
                                    18),
        max_new=env_int("BENCH_SERVING_FRONTDOOR_MAX_NEW", 8))
    print("BENCH_JSON " + json.dumps(out), flush=True)


def _attach_serving_frontdoor(result, budget_s=None):
    return _attach_stage(result, "serving-frontdoor",
                         "--child-serving-frontdoor",
                         SERVING_FRONTDOOR_DEADLINE_S, budget_s)


def _child_serving_disagg():
    """serving-disagg stage: the prefill/decode fleet
    (serving/fleet.py + handoff.py) on a shared-system-prompt workload
    — pins cross-worker bit-identity vs a unified Server, handoff KV
    payload bytes at wire size with the fp32-vs-int8 ratio (~3.6x),
    fleet-wide prefix hit rate with an affinity-on/off A/B (gate:
    affinity >= the single-replica rate), disagg-vs-unified TTFT p50
    and decode tokens/s, and the compile-count pins (ONE decode block
    per decode worker, ONE chunk program per prefill worker). All
    fields non-null on the CPU lane; the TPU child stages the same
    fleet."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.serving.microbench import run_serving_disagg_bench
    out = run_serving_disagg_bench(
        requests_per_group=env_int("BENCH_SERVING_DISAGG_REQUESTS", 6),
        max_new=env_int("BENCH_SERVING_DISAGG_MAX_NEW", 8))
    print("BENCH_JSON " + json.dumps(out), flush=True)


def _attach_serving_disagg(result, budget_s=None):
    return _attach_stage(result, "serving-disagg",
                         "--child-serving-disagg",
                         SERVING_DISAGG_DEADLINE_S, budget_s)


def _child_serving_failover():
    """serving-failover stage: the fleet failure-domain layer
    (serving/transport.py + fleet.py) — kill-one-decode-worker A/B on
    the REAL localhost-TCP SocketTransport with ~1% wire faults armed.
    Pins recovered-stream bit-identity (greedy + seeded-sampled),
    redrive latency p50/p95, goodput with/without the mid-run kill,
    and the handoff retry / (rid, seq)-dedup / transport
    resend-reconnect-CRC counters from the metrics registry. All
    fields non-null on the CPU lane; the TPU child stages the same
    fleet."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.serving.microbench import run_serving_failover_bench
    out = run_serving_failover_bench(
        requests=env_int("BENCH_SERVING_FAILOVER_REQUESTS", 6),
        max_new=env_int("BENCH_SERVING_FAILOVER_MAX_NEW", 24))
    print("BENCH_JSON " + json.dumps(out), flush=True)


def _attach_serving_failover(result, budget_s=None):
    return _attach_stage(result, "serving-failover",
                         "--child-serving-failover",
                         SERVING_FAILOVER_DEADLINE_S, budget_s)


def _child_serving_prefixcache():
    """serving-prefixcache stage: the fleet-wide KV prefix cache
    (serving/prefix_cache.py + the fleet directory/fetch wiring) —
    cold vs warm-local vs warm-remote TTFT on a shared-system-prompt
    ladder, bytes moved over the wire vs prefill flops saved, and the
    fetch/failure/duplicate/eviction counters from the metrics
    registry. Gates: the warm-REMOTE stream is bit-identical to the
    cold locally-prefilled one, warm-remote TTFT strictly beats cold
    (a fetch must cost less than the prefill it replaces), and decode
    + prefill compile counts stay 1 — the fetch adopts through the
    existing scatter program. All fields non-null on the CPU lane; the
    TPU child stages the same fleet."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.serving.microbench import \
        run_serving_prefixcache_bench
    out = run_serving_prefixcache_bench(
        max_new=env_int("BENCH_SERVING_PREFIXCACHE_MAX_NEW", 8),
        sys_len=env_int("BENCH_SERVING_PREFIXCACHE_SYS_LEN", 192))
    print("BENCH_JSON " + json.dumps(out), flush=True)


def _attach_serving_prefixcache(result, budget_s=None):
    return _attach_stage(result, "serving-prefixcache",
                         "--child-serving-prefixcache",
                         SERVING_PREFIXCACHE_DEADLINE_S, budget_s)


def _child_serving_autoscale():
    """serving-autoscale stage: SLO-driven autoscaling
    (serving/loadgen.py + autoscaler.py) — ONE seeded kill-and-burst
    trace replayed against an autoscaled fleet vs static-peak vs
    static-min. Pins bit-identity across scale events (completed
    streams match static-peak token-for-token, greedy rows match
    generate()), the decode-compile count staying 1 through scale-ins,
    the control loop converging (scale up on the burst, repair the
    kill, drain back to the min size), and SLO attainment vs
    worker-ticks — the capacity autoscaling saves. All fields non-null
    on the CPU lane; the TPU child stages the same fleet."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.serving.microbench import run_serving_autoscale_bench
    out = run_serving_autoscale_bench(
        seed=env_int("BENCH_SERVING_AUTOSCALE_SEED", 0),
        horizon=env_int("BENCH_SERVING_AUTOSCALE_HORIZON", 36),
        max_new=env_int("BENCH_SERVING_AUTOSCALE_MAX_NEW", 10))
    print("BENCH_JSON " + json.dumps(out), flush=True)


def _attach_serving_autoscale(result, budget_s=None):
    return _attach_stage(result, "serving-autoscale",
                         "--child-serving-autoscale",
                         SERVING_AUTOSCALE_DEADLINE_S, budget_s)


def _child_serving_recovery():
    """serving-recovery stage: the durable fleet control plane
    (serving/durability.py + fleet.py) — ONE seeded workload run
    clean, then run again with a checkpoint mid-traffic and a
    whole-fleet crash two ticks later, recovered via Fleet.recover.
    Pins bit-identity through the crash (every completed row matches
    the clean arm token-for-token, greedy AND seeded-sampled),
    recovery wall time, journal records replayed, streams redriven,
    decode compiles staying 1 on the recovered arenas, zero leaks.
    All fields non-null on the CPU lane; the TPU child stages the
    same fleet."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.serving.microbench import run_serving_recovery_bench
    out = run_serving_recovery_bench(
        seed=env_int("BENCH_SERVING_RECOVERY_SEED", 0),
        requests=env_int("BENCH_SERVING_RECOVERY_REQUESTS", 6),
        max_new=env_int("BENCH_SERVING_RECOVERY_MAX_NEW", 10))
    print("BENCH_JSON " + json.dumps(out), flush=True)


def _attach_serving_recovery(result, budget_s=None):
    return _attach_stage(result, "serving-recovery",
                         "--child-serving-recovery",
                         SERVING_RECOVERY_DEADLINE_S, budget_s)


def _child_autotune():
    """autotune stage: the Pallas block-size sweep harness
    (ops/pallas/autotune.py) — sweeps every knob that is honest on this
    backend (xent vocab-chunk + paged arena block size on any lane;
    flash/splash blocks only where the kernels dispatch), persists the
    provenance-stamped table, and PROVES a kernel reads it at trace
    time (the xent chunk cap re-derived through the production lookup).
    Also records the effective flash block-choice attribution so sdpa
    A/Bs are attributable to a config, not a guess."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.ops.pallas import flash_attention as fa
    from paddle_tpu.ops.pallas.autotune import run_autotune
    out = run_autotune(
        rows=env_int("BENCH_AUTOTUNE_ROWS", 256),
        vocab=env_int("BENCH_AUTOTUNE_VOCAB", 8192))
    out["autotune_flash_block_choice"] = fa.last_block_choice()
    print("BENCH_JSON " + json.dumps(out), flush=True)


def _attach_autotune(result, budget_s=None):
    return _attach_stage(result, "autotune", "--child-autotune",
                         AUTOTUNE_DEADLINE_S, budget_s)


def _child_serving_tp():
    """serving-tp stage: the slot-pool decode block sharded over a
    simulated 2x4 CPU mesh (serving/microbench.py) — pins exact-mode
    bit-identity, 1-chip vs sharded tokens/s, collective bytes/calls
    per decode step from the metrics registry, and the int8-hop error
    bound every round. The real multi-chip decode win rides the same
    TPConfig when a multi-chip window exists."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.serving.microbench import run_serving_tp_bench
    out = run_serving_tp_bench(
        requests=env_int("BENCH_SERVING_TP_REQUESTS", 6),
        max_new=env_int("BENCH_SERVING_TP_MAX_NEW", 16))
    print("BENCH_JSON " + json.dumps(out), flush=True)


def _attach_serving_tp(result, budget_s=None):
    return _attach_stage(result, "serving-tp", "--child-serving-tp",
                         SERVING_TP_DEADLINE_S, budget_s)


def _provenance():
    """Stamp for every bench artifact: which software stack and source
    rev produced it — so a committed BENCH_*.json is attributable (the
    r0x files predate this stamp; absence of the stamp marks them
    stale). Versions come from package metadata (the parent never
    initializes a jax backend); device kind/count ride the child
    results, where the backend actually lives."""
    import importlib.metadata as md
    def _v(pkg):
        try:
            return md.version(pkg)
        except md.PackageNotFoundError:
            return None
    try:
        rev = subprocess.run(
            ["git", "-C", os.path.dirname(os.path.abspath(__file__)),
             "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10).stdout.strip()
    except (OSError, subprocess.TimeoutExpired):
        rev = None
    return {"jax_version": _v("jax"), "jaxlib_version": _v("jaxlib"),
            "git_rev": rev or None,
            "bench_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                       time.gmtime())}


def _emit_final(result):
    """The parent's ONE final JSON line, provenance-stamped."""
    result.update(_provenance())
    print(json.dumps(result))


def _child_probe():
    """Tiny tunnel-health check: init backend + one 256x256 matmul."""
    import jax
    import jax.numpy as jnp
    dev = jax.devices()[0]
    x = jnp.ones((256, 256))
    float((x @ x).sum())        # forces device round-trip
    print("BENCH_JSON " + json.dumps({"probe": "ok",
                                      "platform": dev.platform}))


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "--child-tpu":
        _child_tpu()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--child-cpu":
        _child_cpu()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--child-probe":
        _child_probe()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--child-comms":
        _child_comms()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--child-passes":
        _child_passes()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--child-observability":
        _child_observability()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--child-serving-tp":
        _child_serving_tp()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--child-serving-spec":
        _child_serving_spec()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--child-serving-quant":
        _child_serving_quant()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--child-serving-frontdoor":
        _child_serving_frontdoor()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--child-serving-megakernel":
        _child_serving_megakernel()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--child-serving-disagg":
        _child_serving_disagg()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--child-serving-failover":
        _child_serving_failover()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--child-serving-prefixcache":
        _child_serving_prefixcache()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--child-serving-autoscale":
        _child_serving_autoscale()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--child-serving-recovery":
        _child_serving_recovery()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--child-autotune":
        _child_autotune()
        return

    errors = []
    try:
        _main_measured(errors)
    except KeyboardInterrupt:
        # the session scripts deadline-SIGINT the whole process group;
        # the one-JSON-line/rc-0 contract must survive that path too
        _emit_final({
            "metric": "llama_pretrain_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
            "tpu_unavailable": True, "interrupted": True,
            "tpu_errors": _err_slots(errors),
            "last_measured_tpu": _last_measured_tpu(),
        })


def _err_slots(errors):
    """First + last error: the probe-retry loop floods the front with
    near-identical lines; the tail holds the real TPU-attempt failure."""
    return errors[:1] if len(errors) <= 1 else [errors[0], errors[-1]]


def _main_measured(errors):
    t_start = time.time()
    # wall budget for the WHOLE bench (session scripts run bench under
    # an outer `timeout`); probe retries must not eat the TPU child's
    # window — and a too-late recovery must skip to the CPU fallback
    # rather than start a doomed heavy run
    total_budget = env_float("BENCH_TOTAL_BUDGET_S", 0) \
        or None     # unset → unbounded: never shrink the child deadline

    def remaining():
        if total_budget is None:
            return float("inf")
        return total_budget - (time.time() - t_start)

    tpu_intended = env_str("JAX_PLATFORMS", "axon") != "cpu"
    tpu_healthy = tpu_intended
    if tpu_intended:
        # a wedged tunnel often recovers within minutes (r3: wedged for
        # hours mid-round, healthy windows either side) — keep probing
        # inside a bounded retry window before surrendering the round's
        # only driver-visible TPU artifact to the CPU fallback
        retry_budget = env_float("BENCH_PROBE_RETRY_S", 600)
        attempt = 0
        while True:
            attempt += 1
            probe, perr = _run_child("--child-probe", PROBE_DEADLINE_S)
            if probe is not None and probe.get("platform") != "cpu":
                break
            errors.append(
                f"probe {attempt}: {perr or 'backend fell back to cpu'}")
            # headroom accounts for the sleep + one more failed probe
            # this iteration may spend before the guard runs again
            if time.time() - t_start > retry_budget or \
                    remaining() < CPU_DEADLINE_S + 2 * PROBE_DEADLINE_S \
                    + 150:
                tpu_healthy = False
                break
            time.sleep(min(120, retry_budget / 4))
    if tpu_healthy:
        for attempt in range(TPU_ATTEMPTS):
            # leave the CPU fallback its window; a late tunnel recovery
            # gets a shortened child deadline instead of a doomed run
            child_deadline = min(TPU_DEADLINE_S,
                                 remaining() - CPU_DEADLINE_S - 30)
            if child_deadline < 120:
                errors.append("tpu: recovered too late in the budget")
                break
            result, err = _run_child("--child-tpu", child_deadline)
            if result is not None:
                result = _attach_comms(result, remaining())
                result = _attach_passes(result, remaining())
                result = _attach_observability(result, remaining())
                result = _attach_serving_tp(result, remaining())
                result = _attach_serving_spec(result, remaining())
                result = _attach_serving_quant(result, remaining())
                result = _attach_serving_megakernel(result, remaining())
                result = _attach_serving_frontdoor(result, remaining())
                result = _attach_serving_disagg(result, remaining())
                result = _attach_serving_failover(result, remaining())
                result = _attach_serving_prefixcache(result, remaining())
                result = _attach_serving_autoscale(result, remaining())
                result = _attach_serving_recovery(result, remaining())
                _emit_final(_attach_autotune(result, remaining()))
                return
            errors.append(f"tpu attempt {attempt + 1}: {err}")
            time.sleep(5)

    result, err = _run_child(
        "--child-cpu", max(60.0, min(CPU_DEADLINE_S, remaining() - 10)))
    if result is not None:
        if tpu_intended:
            # a TPU run was intended and failed/skipped — mark the outage
            result["tpu_unavailable"] = True
            result["chip"] = "cpu-fallback"
            # first + last error: the retry loop floods the front with
            # near-identical probe lines, the tail has the real failure
            result["tpu_errors"] = _err_slots(errors)
            result["last_measured_tpu"] = _last_measured_tpu()
            # every probe/contact this round, timestamped, with outcomes
            # — the wedge-is-environmental evidence chain (VERDICT r4 #1)
            result["tunnel_log"] = "TUNNEL_r05.json"
        result = _attach_comms(result, remaining())
        result = _attach_passes(result, remaining())
        result = _attach_observability(result, remaining())
        result = _attach_serving_tp(result, remaining())
        result = _attach_serving_spec(result, remaining())
        result = _attach_serving_quant(result, remaining())
        result = _attach_serving_megakernel(result, remaining())
        result = _attach_serving_frontdoor(result, remaining())
        result = _attach_serving_disagg(result, remaining())
        result = _attach_serving_failover(result, remaining())
        result = _attach_serving_prefixcache(result, remaining())
        result = _attach_serving_autoscale(result, remaining())
        result = _attach_serving_recovery(result, remaining())
        _emit_final(_attach_autotune(result, remaining()))
        return
    # last resort: still one JSON line, rc 0, explicit marker
    _emit_final({
        "metric": "llama_pretrain_tokens_per_sec_per_chip",
        "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
        "tpu_unavailable": True, "cpu_fallback_failed": True,
        "tpu_errors": _err_slots(errors),
        "cpu_error": (err or "")[:500],
    })


if __name__ == "__main__":
    main()
